"""Single-process no-op engine.

TPU-native equivalent of the reference's EmptyEngine
(reference: src/engine_empty.cc:19-83): world size 1, collectives are
identities, checkpoints are kept in memory so programs written against the
full API run unmodified on one process.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from rabit_tpu.engine.interface import Engine
from rabit_tpu.ops import ReduceOp
from rabit_tpu.utils.checks import check


class EmptyEngine(Engine):
    def __init__(self) -> None:
        self._version = 0
        self._global: Optional[bytes] = None
        self._local: Optional[bytes] = None

    def init(self, params: dict) -> None:
        pass

    def shutdown(self) -> None:
        pass

    @property
    def rank(self) -> int:
        return 0

    @property
    def world_size(self) -> int:
        return 1

    def allreduce(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        codec: bool = True,
    ) -> np.ndarray:
        if prepare_fun is not None:
            prepare_fun()
        return buf

    def broadcast(self, data: Optional[bytes], root: int) -> bytes:
        check(root == 0, "EmptyEngine: root must be 0 in a world of 1")
        check(data is not None, "EmptyEngine: root rank must supply data")
        return data

    def load_checkpoint(self) -> tuple[int, Optional[bytes], Optional[bytes]]:
        return (self._version, self._global, self._local)

    def checkpoint(
        self,
        global_model: bytes,
        local_model: Optional[bytes] = None,
        lazy_global: Optional[Callable[[], bytes]] = None,
    ) -> None:
        if global_model is None and lazy_global is not None:
            global_model = lazy_global()
        self._global = global_model
        self._local = local_model
        self._version += 1

    @property
    def version_number(self) -> int:
        return self._version
