"""MPI-backed engine (non-fault-tolerant) over mpi4py or the builtin
ctypes binding.

TPU-native equivalent of the reference's MPI engine
(reference: src/engine_mpi.cc:20-205 — IEngine over MPI::COMM_WORLD,
no checkpointing/recovery).  Useful where an MPI runtime already
manages the job (HPC clusters); on TPU pods prefer the xla engine.
mpi4py is not bundled in the TPU image, so the engine falls back to
``rabit_tpu.engine.libmpi`` — a ctypes binding straight to the system
libmpi — whenever mpi4py is absent; ``mpi_available()`` probes for
either runtime.
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from rabit_tpu.engine.interface import Engine
from rabit_tpu.ops import ReduceOp
from rabit_tpu.utils.checks import check


def mpi_available() -> bool:
    try:
        import mpi4py  # noqa: F401
        return True
    except ImportError:
        pass
    from rabit_tpu.engine import libmpi

    return libmpi.available()


class MPIEngine(Engine):
    """Collectives over MPI.COMM_WORLD via mpi4py (or the builtin
    libmpi ctypes binding when mpi4py is not installed)."""

    def __init__(self) -> None:
        try:
            from mpi4py import MPI
            comm = MPI.COMM_WORLD
        except ImportError as e:
            from rabit_tpu.engine import libmpi

            if not libmpi.available():
                raise RuntimeError(
                    "rabit_engine=mpi needs mpi4py or a system libmpi, "
                    "neither of which is present; use "
                    "rabit_engine=native or xla") from e
            MPI = libmpi
            comm = libmpi.comm_world()
        self._mpi = MPI
        self._comm = comm
        self._version = 0
        self._global: bytes = b""
        self._local: bytes = b""

    def init(self, params: dict) -> None:
        pass  # the MPI runtime did the rendezvous

    def shutdown(self) -> None:
        self._comm.Barrier()

    @property
    def rank(self) -> int:
        return self._comm.Get_rank()

    @property
    def world_size(self) -> int:
        return self._comm.Get_size()

    def is_distributed(self) -> bool:
        return self.world_size != 1

    _OPS = {
        ReduceOp.MAX: "MAX", ReduceOp.MIN: "MIN", ReduceOp.SUM: "SUM",
        ReduceOp.PROD: "PROD", ReduceOp.BITOR: "BOR",
        ReduceOp.BITAND: "BAND", ReduceOp.BITXOR: "BXOR",
    }

    def allreduce(self, buf: np.ndarray, op: ReduceOp,
                  prepare_fun: Optional[Callable[[], None]] = None,
                  codec: bool = True) -> np.ndarray:
        check(op in self._OPS, f"mpi engine: unsupported op {op}")
        if prepare_fun is not None:
            prepare_fun()
        mpi_op = getattr(self._mpi, self._OPS[op])
        self._comm.Allreduce(self._mpi.IN_PLACE, buf, op=mpi_op)
        return buf

    def broadcast(self, data: Optional[bytes], root: int) -> bytes:
        return self._comm.bcast(data, root=root)

    def allgather(self, buf: np.ndarray) -> np.ndarray:
        out = np.empty((self.world_size,) + buf.shape, buf.dtype)
        self._comm.Allgather(buf, out)
        return out

    # Checkpoints are process-local (the MPI engine is not fault tolerant,
    # like the reference's, src/engine_mpi.cc:56-72).
    def load_checkpoint(self):
        if self._version == 0:
            return 0, None, None
        return self._version, self._global, self._local or None

    def checkpoint(self, global_model, local_model=None, lazy_global=None):
        if global_model is None and lazy_global is not None:
            global_model = lazy_global()
        self._global = global_model or b""
        self._local = local_model or b""
        self._version += 1

    @property
    def version_number(self) -> int:
        return self._version

    def tracker_print(self, msg: str) -> None:
        # No tracker in an MPI job: print locally, rank-tagged, from any
        # rank (matching the interface contract that no rank's message is
        # dropped).
        print(f"@tracker[{self.rank}] {msg}", flush=True)
