"""XLA engine: the TPU data plane.

This is the engine the reference cannot have: collectives execute on the
accelerator interconnect (ICI/DCN) as XLA programs instead of over host
TCP sockets.  The design splits rabit's two planes the TPU-native way
(SURVEY.md §7):

* **control plane** — rank rendezvous, byte broadcast, checkpoint
  replication, TrackerPrint, fault tolerance — delegates to an inner host
  engine (the native C++ robust engine, or the pure-Python socket engine)
  speaking the tracker protocol, exactly like the reference's control
  path (reference: src/allreduce_base.cc:138-158, tracker/rabit_tracker.py).
* **data plane** — ``jax.Array`` allreduce/allgather — runs as compiled
  XLA collectives over a process-level mesh.  The reference's equivalent
  is the hand-scheduled socket tree loop (reference:
  src/allreduce_base.cc:326-491); here XLA schedules onto the torus.

Numpy buffers route through the inner host engine: that path is
fault-tolerant (result caching + replay, reference:
src/allreduce_robust.cc:73-105) and latency-bound payloads don't benefit
from the device round-trip.  ``jax.Array`` buffers stay device-resident
and ride ICI; this bulk path is *not* replayed on failure — the
checkpoint/recover contract covers it at iteration granularity, which is
how the reference's apps use the API anyway (checkpoint per iteration,
reference: rabit-learn/kmeans/kmeans.cc:121-157).

Bootstrap: the inner engine's tracker rendezvous assigns the rank; rank 0
then picks a JAX coordinator address and broadcasts it over the control
plane; every process calls ``jax.distributed.initialize`` with its
tracker rank as the process id, so control-plane ranks and mesh positions
agree by construction.  If JAX is already multi-process (TPU pod launched
through its own orchestration), the engine adopts JAX's identity instead.
"""
from __future__ import annotations

import os
import socket as pysocket
import time
from typing import Callable, Optional

import numpy as np

from rabit_tpu import obs
from rabit_tpu.engine.interface import CollectiveHandle, Engine
from rabit_tpu.ops import ReduceOp
from rabit_tpu.utils.checks import check

PROC_AXIS = "proc"


# Transport failures from the CPU-collectives backend surface as bare
# ValueError("UNKNOWN: Gloo all-reduce failed ... Connection reset by
# peer") rather than a typed runtime error — recognize them by message.
_TRANSPORT_MARKERS = ("gloo", "connection reset", "connection refused",
                      "socket closed", "unavailable:", "deadline exceeded")


def _is_runtime_failure(e: BaseException) -> bool:
    """True for *runtime/peer* failures of a device collective (worth
    degrading to the host path); programming errors (shape/dtype bugs,
    tracer misuse) must propagate instead.  Resolved lazily so importing
    this module never imports jax.

    The message-marker fallback is restricted to the exception types the
    collective runtime actually raises (Gloo failures surface as bare
    ``ValueError``, XLA ones as ``RuntimeError`` subclasses) so a
    programming error that merely *mentions* a marker word is not
    silently swallowed into the degraded path."""
    try:
        import jax.errors

        if isinstance(e, (jax.errors.JaxRuntimeError, OSError)):
            return True
    except (ImportError, AttributeError):  # pragma: no cover
        pass
    if not isinstance(e, (ValueError, RuntimeError, OSError)):
        return False
    msg = str(e).lower()
    return any(m in msg for m in _TRANSPORT_MARKERS)


def _free_port() -> int:
    from rabit_tpu.utils.net import free_port

    return free_port()


class XLAEngine(Engine):
    def __init__(self) -> None:
        self._inner: Optional[Engine] = None
        self._rank = 0
        self._world = 1
        self._job_id = "default"   # resolved in init() (multi-tenant)
        self._adopted_jax = False
        # Pure adopt mode (no tracker): numpy/bytes ops must ride
        # device collectives; there is no inner transport.  The MIXED
        # mode (tracker + externally initialized JAX) keeps the
        # fault-tolerant host transport: degradation works, but the
        # device plane can never be re-formed (the engine does not
        # own the external runtime) — _maybe_reform gates on
        # _adopted_jax for that reason.
        self._no_host_transport = False
        self._we_initialized_jax = False
        self._proc_mesh = None
        self._reduce_cache: dict = {}
        self._degraded = False
        self._reform_enabled = True
        self._device_epoch = 0
        self._init_timeout = 300
        self._custom_client = False
        self._svc_tracker_hosted = False
        # Device-plane allreduce lowering: "psum" (XLA's own ICI
        # collective, the default) or "pallas_ring" (the credit-flow
        # remote-DMA ring in ops/ring_allreduce.py) for payloads at or
        # above rabit_pallas_min_bytes — the chunked per-link ring the
        # reference hand-pipelines (src/allreduce_base.h:256-295),
        # expressed as a kernel the scheduler can't deschedule.
        self._device_impl = "psum"
        self._pallas_min_bytes = 1 << 20
        # observable path counters (tests assert post-reform collectives
        # ride the device mesh again, not the degraded host path).
        # Named path_stats because Engine.stats() is the telemetry
        # snapshot method.
        self.path_stats = {"device_ops": 0, "host_ops": 0}
        # Telemetry (rabit_tpu.obs): resolved in init().
        self._obs_on = False
        self._obs_dir: Optional[str] = None
        self._metrics: Optional[obs.Metrics] = None
        self._trace: Optional[obs.EventTrace] = None
        self._obs_log = obs.log.Logger("xla", lambda: {"rank": self._rank})

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def init(self, params: dict) -> None:
        import jax

        cfg = obs.configure(params)
        self._obs_on = cfg.enabled
        self._obs_dir = cfg.obs_dir
        self._metrics = obs.Metrics()
        self._trace = obs.EventTrace(capacity=cfg.trace_capacity)
        self._device_impl = str(
            params.get("rabit_device_impl")
            or os.environ.get("RABIT_DEVICE_IMPL", "psum")).lower()
        check(self._device_impl in ("psum", "pallas_ring"),
              "rabit_device_impl must be psum|pallas_ring, got %r",
              self._device_impl)
        min_bytes = params.get("rabit_pallas_min_bytes")
        if min_bytes is None:
            min_bytes = os.environ.get("RABIT_PALLAS_MIN_BYTES", 1 << 20)
        try:
            self._pallas_min_bytes = int(min_bytes)
        except (TypeError, ValueError):
            check(False, "rabit_pallas_min_bytes must be an integer "
                  "byte count, got %r", min_bytes)
        uri = params.get("rabit_tracker_uri") or os.environ.get(
            "RABIT_TRACKER_URI")
        port = params.get("rabit_tracker_port") or os.environ.get(
            "RABIT_TRACKER_PORT", 0)
        self._tracker_addr = (str(uri), int(port))
        # Tenant identity: must match what the INNER engine registers
        # under, or the formation barrier / jaxsvc lookups would land in
        # a different job than the rendezvous (params win over env,
        # exactly like pysocket's resolution).
        self._job_id = str(params.get("rabit_job_id")
                           or os.environ.get("RABIT_JOB_ID")
                           or "default")
        have_tracker = bool(uri)
        # Mid-job-relaunch detection: RABIT_RELAUNCH counts restarts of
        # any cause (kill-point or watchdog); rabit_num_trial alone would
        # miss watchdog restarts, whose incarnations must also come up
        # degraded.
        trial = max(int(params.get("rabit_num_trial")
                        or os.environ.get("RABIT_NUM_TRIAL", 0)),
                    int(os.environ.get("RABIT_RELAUNCH", 0)))
        if have_tracker:
            # MIXED mode (tracker + externally-initialized JAX runtime):
            # the platform fixed jax.process_index() before we ran, so
            # register with task_id = that index; with the tracker's
            # RABIT_TRACKER_PIN_RANKS=1 the control-plane rank then
            # matches the device numbering (doc/scaling.md recipe).
            # An explicit rabit_task_id always wins.
            mixed = jax.distributed.is_initialized()
            # presence test, not truthiness: an explicit task_id of 0
            # must win over the automatic registration, or the rank-0
            # worker of a user-pinned launch would collide with whichever
            # worker legitimately owns its jax.process_index()
            has_tid = (params.get("rabit_task_id") is not None
                       and str(params.get("rabit_task_id")) != "") or \
                os.environ.get("RABIT_TASK_ID", "") != ""
            if mixed and not has_tid:
                params = dict(params)
                params["rabit_task_id"] = str(jax.process_index())
            self._inner = self._make_inner(params)
            self._inner.init(params)
            self._rank = self._inner.rank
            self._world = self._inner.world_size
            # The tracker flags mid-job re-registrations too, so platform
            # restarts with a clean environment are still detected.
            if getattr(self._inner, "was_relaunched", False):
                trial = max(trial, 1)
            self._reform_enabled = str(
                params.get("rabit_device_reform")
                or os.environ.get("RABIT_DEVICE_REFORM", "1")) not in (
                    "0", "false", "no")
            try:
                self._init_timeout = max(
                    30, 2 * int(float(params.get("rabit_timeout_sec")
                                      or os.environ.get(
                                          "RABIT_TIMEOUT_SEC", 150))))
            except ValueError:
                self._init_timeout = 300
            if self._world > 1:
                if mixed:
                    # MIXED mode — set on EVERY incarnation (a relaunch
                    # must gate out of _maybe_reform and the ordered
                    # shutdown exactly like the adopted survivors do, or
                    # its host-plane protocol ops would have no partner).
                    self._adopted_jax = True
                    self._log_stderr(
                        "MIXED mode: adopting the externally-initialized "
                        "JAX runtime under a tracker control plane — host "
                        "transport stays fault-tolerant (degradation "
                        "works), but the device plane is owned by the "
                        "external runtime and can NEVER be re-formed "
                        "after a failure")
                    if trial > 0:
                        # Relaunch: whatever external device plane this
                        # incarnation re-joined, the survivors' group no
                        # longer includes the previous life — permanent
                        # host-transport mode (no reform in mixed mode).
                        self._degraded = True
                elif trial > 0:
                    # Mid-job relaunch (keepalive restart): the device mesh
                    # of the original incarnation died with this worker and
                    # the surviving processes' JAX group cannot admit a new
                    # member.  Come up degraded — all jax.Array collectives
                    # ride the fault-tolerant host transport — and resume
                    # from the checkpoint.  Full device-plane speed returns
                    # at the next checkpoint boundary, where every rank
                    # agrees to tear down the broken group and re-form a
                    # fresh one (_maybe_reform; the reference's recovered
                    # jobs likewise return to full speed,
                    # reference: src/allreduce_robust.cc:426-453).
                    #
                    # Known narrow window: a worker that completed the
                    # tracker round but died BEFORE the JAX group finished
                    # forming also arrives here, and the survivors (still
                    # inside _init_jax_distributed) then time out at
                    # initialize — surfaced as a failed formation, after
                    # which the survivors run degraded until the next
                    # checkpoint boundary re-forms the group.
                    self._degraded = True
                else:
                    try:
                        self._init_jax_distributed(params)
                    except Exception as e:  # noqa: BLE001
                        if not _is_runtime_failure(e):
                            raise
                        self._log_stderr(
                            "device group formation failed "
                            f"({type(e).__name__}: {e}); starting degraded")
                        self._drop_distributed_state()
                        self._degraded = True
        else:
            # No tracker: adopt whatever world JAX already lives in
            # (single process, or a pod slice launched by its own runtime).
            from rabit_tpu.engine.empty import EmptyEngine

            self._inner = EmptyEngine()
            self._inner.init(params)
            self._rank = jax.process_index()
            self._world = jax.process_count()
            self._adopted_jax = self._world > 1
            self._no_host_transport = self._world > 1
        if self._world > 1 and not self._degraded:
            if self._adopted_jax and not self._no_host_transport:
                self._build_proc_mesh_mixed()
            else:
                self._build_proc_mesh()

    def _make_inner(self, params: dict) -> Engine:
        name = params.get("rabit_inner_engine")
        if name is None:
            try:
                from rabit_tpu.engine.native import native_available

                if native_available():
                    name = "native"
            except ImportError:
                pass
            # No native library: the degraded/host control plane still
            # gets full cache/replay fault tolerance from the pure-
            # Python robust engine (rabit_tpu/engine/robust.py).
            if name is None:
                name = "pyrobust"
        if name in ("xla", "mpi"):
            raise ValueError(
                f"engine {name!r} cannot back the XLA data plane")
        from rabit_tpu.engine import _make_engine

        # Shared name->class registry; "native" resolves to the robust
        # variant there, which is exactly what the inner engine needs.
        return _make_engine(name, params)

    def _init_jax_distributed(self, params: dict) -> None:
        """Form the JAX process group using control-plane rank/broadcast."""
        import jax

        if jax.distributed.is_initialized():
            # Defensive only: init() routes pre-initialized runtimes to
            # the mixed-mode branch before ever calling this method.
            self._adopted_jax = True
            return
        # Only meaningful on CPU backends (tests, DCN-only hosts); inert
        # on TPU.  Must be set before backend initialization.
        impl = params.get("rabit_jax_cpu_collectives", "gloo")
        try:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        except Exception as e:  # noqa: BLE001 — config retired/renamed
            self._obs_log.debug("jax_cpu_collectives_implementation "
                                "unavailable: %s", e)
        # Fault tolerance lives in the host-side robust protocol, so a
        # peer death must surface as a failed collective (-> degrade to
        # host transport), NOT as the coordination service fatally
        # terminating the survivors.
        try:
            jax.config.update("jax_enable_recoverability", True)
        except Exception as e:  # noqa: BLE001 — older jax, no flag
            self._obs_log.debug("jax_enable_recoverability unavailable: "
                                "%s", e)
        if self._private_bindings_ok():
            # Every rank resolves the SAME tracker-hosted service by key:
            # the init-time coordinator exchange runs entirely over the
            # tracker, so version-span 0 contains no engine-internal
            # collectives and a worker relaunched before the first
            # checkpoint replays a span aligned with the survivors'.
            coord = self._request_tracker_service("init")
            self._svc_tracker_hosted = bool(coord)
        else:
            coord = ""
        if not coord:
            # Legacy fallback (no private client bindings, or a tracker
            # that cannot host): rank 0 hosts, address distributed over
            # the host plane.  This puts one broadcast into span 0; on
            # such installs rank-0 death is unrecoverable anyway (the
            # round-2 contract), so the narrower replay alignment is
            # accepted there.
            coord = self._broadcast_fresh_coordinator()
        if os.environ.get("RABIT_XLA_DIE_FORMATION", "") == str(self._rank):
            # Fault-injection hook (XLA death matrix): die INSIDE the
            # formation window — tracker round + coordinator resolution
            # complete, formation barrier not yet posted, JAX group not
            # formed.  The survivors must learn of the death on the
            # control plane (formation barrier abort), start degraded,
            # and re-form at the next checkpoint boundary.  Only
            # reachable on the first life: relaunches take the degraded
            # branch and reforms go through _maybe_reform, neither of
            # which calls this method.
            self._log_stderr(
                f"rank {self._rank} dying in the formation window "
                "(RABIT_XLA_DIE_FORMATION)")
            os._exit(254)
        if not self._formation_barrier():
            # Someone died (or the barrier timed out) before formation
            # could complete: entering the device-group registration now
            # would block unrecoverably (see protocol.CMD_FORMBAR) —
            # start degraded; the first checkpoint re-forms the plane.
            self._log_stderr(
                "formation barrier aborted — starting degraded")
            self._degraded = True
            return
        # First formation is the one spot where a member death leaves the
        # survivors blind (no host-protocol traffic to error out of), and
        # a client stuck in a doomed registration is in danger: when a
        # co-registrant dies, the coordination service's heartbeat
        # detection pushes a FATAL to the still-blocked clients
        # (client.h:80 — mid-registration deaths are not covered by the
        # recoverable-task semantics that protect formed groups).  So the
        # first-formation timeout is SHORT: survivors abandon the doomed
        # barrier, drop their clients (stopping the error-polling
        # thread), and start degraded before either the service's
        # heartbeat window or the launcher watchdog can act; the first
        # checkpoint boundary re-forms the plane.  Raise on pods where
        # honest formation needs longer.
        raw = (params.get("rabit_form_timeout_sec")
               or os.environ.get("RABIT_FORM_TIMEOUT_SEC"))
        if raw is not None:
            # explicitly configured: honored as-is (pods with slow
            # honest formation RAISE it, per doc/parameters.md)
            try:
                form_timeout = int(float(raw))
            except ValueError:
                form_timeout = 10
        else:
            form_timeout = min(10, self._init_timeout)
        self._connect_distributed(coord, init_timeout=form_timeout)
        self._we_initialized_jax = True

    def _formation_barrier(self) -> bool:
        """Post the tracker's formation barrier (protocol.CMD_FORMBAR):
        the LAST act before the blocking jaxlib group registration.
        True = every worker is alive and about to register too; False =
        formation is doomed (a member died / barrier timed out) — the
        caller must start degraded instead of blocking.  Fails safe:
        any tracker-path error counts as an abort."""
        try:
            from rabit_tpu.tracker import protocol as P

            sock = pysocket.create_connection(
                self._tracker_addr, timeout=self._init_timeout + 60)
            try:
                sock.settimeout(self._init_timeout + 60)
                P.send_hello(
                    sock, P.CMD_FORMBAR,
                    os.environ.get("RABIT_TASK_ID", str(self._rank)),
                    self._world, job=self._job_id)
                return P.recv_u32(sock) == 1
            finally:
                sock.close()
        except Exception as e:  # noqa: BLE001 — fail safe to degraded
            self._log_stderr(
                f"formation barrier failed ({type(e).__name__}: {e})")
            return False

    def _request_tracker_service(self, key: str = "") -> str:
        """Ask the tracker for a JAX coordination service (cmd=jaxsvc);
        returns "host:port" or "" if it cannot.  ``key == ""`` makes a
        fresh service (one per device-plane reform); a non-empty key
        (the init-time "init") is create-or-get tracker-side, so every
        rank resolves the same service with no worker-to-worker op."""
        try:
            from rabit_tpu.tracker import protocol as P

            sock = pysocket.create_connection(self._tracker_addr, timeout=30)
            try:
                P.send_hello(sock, P.CMD_JAXSVC, key, self._world,
                             job=self._job_id)
                port = P.recv_u32(sock)
            finally:
                sock.close()
            return f"{self._tracker_addr[0]}:{port}" if port else ""
        except Exception as e:  # noqa: BLE001
            self._log_stderr(
                f"tracker jaxsvc request failed ({type(e).__name__}: {e})")
            return ""

    @staticmethod
    def _private_bindings_ok() -> bool:
        """True when jaxlib exposes the client constructor (with the
        kwargs we need) for joining an EXTERNAL coordination service.
        Probed BEFORE choosing the coordinator host: without the
        bindings, the public-API fallback makes rank 0 host the service
        itself, so the coordinator address must then be rank-0-local —
        a tracker-hosted address would have rank 0 binding a port that
        is already the tracker's (or on the wrong machine entirely).

        The probe is a feature TRY-CALL: construct (never connect) a
        client with the kwargs the recoverable recipe needs.  nanobind
        rejects unknown kwargs with TypeError before any side effect,
        construction performs no network IO (``connect()`` is a separate
        call), and ``shutdown_on_destruction=False`` keeps the immediate
        drop RPC-free.  ``inspect.signature`` is useless here (nanobind
        reports ``(*args, **kwargs)``) and doc-grep broke on docstring
        wording churn."""
        try:
            from jax._src import distributed as _jd  # noqa: F401
            from jax._src.lib import _jax as jaxlib_ext

            fn = jaxlib_ext.get_distributed_runtime_client
        except (ImportError, AttributeError):
            return False
        try:
            client = fn("127.0.0.1:1", 0, init_timeout=1,
                        shutdown_on_destruction=False, recoverable=True)
            del client
            return True
        except TypeError:
            # unknown kwarg / changed arity — the recipe is unavailable
            return False
        except Exception:  # noqa: BLE001
            # kwargs were ACCEPTED; construction failed for environmental
            # reasons — report available and let the real call surface it
            return True

    def _broadcast_fresh_coordinator(self) -> str:
        """Rank 0 obtains a coordinator endpoint — preferring a
        TRACKER-HOSTED coordination service, so the service's lifetime is
        decoupled from every worker's (any worker death, rank 0
        included, is then a recoverable peer failure) — and everyone
        learns it over the host control plane.  The payload carries a
        T|/L| marker so all members agree on where the service lives."""
        if self._rank == 0:
            if self._private_bindings_ok():
                coord = self._request_tracker_service()
            else:
                coord = ""
                self._log_stderr(
                    "jaxlib private distributed-client bindings "
                    "unavailable — FALLING BACK to rank-0-hosted "
                    "coordination service; rank-0 death will NOT be "
                    "recoverable")
            payload = (f"T|{coord}" if coord else
                       f"L|{self._coordinator_host()}:{_free_port()}"
                       ).encode()
        else:
            payload = None
        marker, _, coord = self._inner.broadcast(
            payload, root=0).decode().partition("|")
        self._svc_tracker_hosted = marker == "T"
        return coord

    def _connect_distributed(self, coord: str,
                             init_timeout: int | None = None) -> None:
        """Join the JAX coordination service at ``coord``.

        Built on the jaxlib distributed-runtime bindings directly
        because every rank here is a CLIENT — the service itself runs in
        the tracker (``jax.distributed.initialize`` would insist on
        process 0 hosting it, re-coupling the coordinator to a worker's
        lifetime).  ``recoverable=True`` keeps peer deaths non-fatal
        (they surface as failed collectives -> degrade -> re-form; the
        reference survives any single death the same way,
        reference: src/allreduce_robust.cc:426-453);
        ``shutdown_on_destruction=False`` keeps a dropped client's
        destructor from RPC-ing a dead service.  Falls back to the
        public API (rank 0 hosting, round-2 behavior) if the private
        bindings move."""
        import jax

        try:
            from jax._src import distributed as jdist
            from jax._src.lib import _jax as jaxlib_ext

            state = jdist.global_state
            check(state.client is None,
                  "XLA engine: JAX distributed client already exists")
            if (self._rank == 0 and not self._svc_tracker_hosted
                    and state.service is None):
                bind = "[::]:" + coord.rsplit(":", 1)[1]
                # long barrier deadline for the same reason as the
                # tracker-hosted service: a formation-window death must
                # surface as the clients' local timeouts, not a
                # service-pushed fatal (client.h:80)
                try:
                    state.service = \
                        jaxlib_ext.get_distributed_runtime_service(
                            bind, self._world,
                            cluster_register_timeout=24 * 3600)
                except TypeError:  # older jaxlib without the kwarg
                    state.service = \
                        jaxlib_ext.get_distributed_runtime_service(
                            bind, self._world)
            client = jaxlib_ext.get_distributed_runtime_client(
                coord, self._rank,
                init_timeout=init_timeout or self._init_timeout,
                use_compression=True,
                shutdown_on_destruction=False,
                recoverable=True)
            client.connect()
            self._log_stderr(f"rank {self._rank} joined coordination "
                             f"service {coord}")
            state.client = client
            state.coordinator_address = coord
            state.num_processes = self._world
            state.process_id = self._rank
            self._custom_client = True
        except (ImportError, AttributeError, TypeError) as e:
            # Private bindings changed shape — use the public API (rank 0
            # hosts the service; its death is then fatal to survivors,
            # the round-2 contract).
            self._log_stderr(
                f"private distributed-client path failed "
                f"({type(e).__name__}: {e}) — FALLING BACK to public "
                "jax.distributed.initialize; rank-0 death will NOT be "
                "recoverable")
            self._svc_tracker_hosted = False
            try:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=self._world,
                    process_id=self._rank,
                    initialization_timeout=(init_timeout
                                            or self._init_timeout),
                )
            except TypeError:  # older jax without the kwarg
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=self._world,
                    process_id=self._rank,
                )
            self._custom_client = False

    def _drop_distributed_state(self) -> None:
        """Reset jax.distributed bookkeeping WITHOUT the disconnect RPC
        (the coordination service is known dead — rank 0's incarnation
        that owned it is gone; an RPC would block and, under the default
        callback, fatally terminate this process)."""
        try:
            from jax._src import distributed as jdist

            state = jdist.global_state
            state.client = None
            state.service = None
            state.coordinator_address = None
        except (ImportError, AttributeError):  # pragma: no cover
            pass
        self._we_initialized_jax = False

    def _shutdown_distributed_ordered(self) -> None:
        """Disconnect from a LIVE coordination service with the teardown
        race closed: followers disconnect while the coordinator-owning
        rank 0 is provably alive (host barrier between the waves)."""
        import jax

        self._control_barrier()
        if self._rank != 0 and self._we_initialized_jax:
            try:
                jax.distributed.shutdown()
            except Exception as e:  # noqa: BLE001
                self._log_stderr(
                    f"distributed shutdown failed ({type(e).__name__}: "
                    f"{e}); dropping state")
                self._drop_distributed_state()
        self._control_barrier()
        if self._rank == 0 and self._we_initialized_jax:
            try:
                jax.distributed.shutdown()
            except Exception as e:  # noqa: BLE001
                self._log_stderr(
                    f"distributed shutdown failed ({type(e).__name__}: "
                    f"{e}); dropping state")
                self._drop_distributed_state()
        self._we_initialized_jax = False

    @staticmethod
    def _log_stderr(msg: str) -> None:
        import sys

        print(f"[rabit_tpu] xla engine: {msg}", file=sys.stderr, flush=True)

    # ------------------------------------------------------------------
    # device-plane re-formation
    # ------------------------------------------------------------------
    @property
    def device_epoch(self) -> int:
        """Bumped every time the device plane is re-formed.  Device
        arrays created under an older epoch are invalid — apps re-upload
        their shards when the epoch moves (the device-side analogue of
        the reference's reload-from-checkpoint after recovery)."""
        return self._device_epoch

    def _maybe_reform(self) -> None:
        """Re-form the device plane if any rank is degraded.

        Runs at the checkpoint boundary (every rank calls checkpoint()
        once per iteration, so this is a consensus point; a relaunched
        incarnation is always degraded, which drags every healthy
        survivor into the reform).  Protocol, all ranks symmetric:

        1. host-plane MAX-allreduce of per-rank state flags
           (bit0 degraded, bit1 member-of-current-JAX-group, bit2
           member's group used a tracker-hosted service);
        2. if nobody is degraded -> done (one small host op per
           checkpoint);
        3. tear down the old group — ordered disconnect when the old
           coordination service is still alive (tracker-hosted, or its
           rank-0 owner survived), raw state drop when it died;
        4. destroy device backends (compiled executables and device
           arrays of the old epoch die with them);
        5. rank 0 obtains a fresh coordination service (tracker-hosted
           when possible) and broadcasts it over the host plane;
           everyone re-initializes, rebuilds the process mesh, clears
           the collective cache, bumps device_epoch.

        A failed re-formation (e.g. another death mid-reform) leaves
        every reachable rank degraded; the next checkpoint retries with
        a fresh coordinator.  Matches the reference's recovered-job
        full-speed semantics (src/allreduce_robust.cc:426-453)."""
        if (self._world <= 1 or self._adopted_jax or self._inner is None
                or not self._reform_enabled):
            return
        import jax
        import jax.extend  # jax.extend is not imported by bare `import jax`

        flags = np.zeros(self._world, np.uint8)
        mine = (1 if self._degraded else 0) | (
            2 if self._we_initialized_jax else 0) | (
            4 if self._we_initialized_jax and self._svc_tracker_hosted
            else 0)
        flags[self._rank] = mine
        self._inner.allreduce(flags, ReduceOp.MAX)
        if not (flags & 1).any():
            return
        # every rank derives these from the SHARED flags, so the branch
        # structure (and its control-plane op sequence) is identical on
        # members and relaunched incarnations alike
        members_exist = bool((flags & 2).any())
        service_alive = bool((flags & 4).any()) or bool(flags[0] & 2)
        self._log_stderr(
            f"re-forming device plane (degraded ranks: "
            f"{[int(r) for r in np.flatnonzero(flags & 1)]}, old service "
            f"{'alive' if members_exist and service_alive else 'dead'})")
        if members_exist and service_alive:
            # ordered disconnect; ranks that were never members of the
            # old group (relaunched incarnations) drop their (empty)
            # state but MUST still join both barriers — every rank's
            # control-plane op sequence stays identical
            if not self._we_initialized_jax:
                self._drop_distributed_state()
            self._shutdown_distributed_ordered()
        else:
            self._drop_distributed_state()
        try:
            jax.extend.backend.clear_backends()
        except Exception as e:  # noqa: BLE001  pragma: no cover
            self._log_stderr(
                f"clear_backends failed ({type(e).__name__}: {e})")
        self._proc_mesh = None
        self._reduce_cache.clear()
        # NOTE: rank 0 must request a service even when the flags op was
        # replayed — if the old rank 0 died MID-round, the survivors are
        # still pending in this broadcast and will receive our payload
        # fresh (we then join their in-flight re-formation below); only
        # a fully-completed round serves the broadcast from cache, and
        # then the unused service is discarded (retained by the tracker,
        # one per replayed-round-on-rank-0-relaunch — rare and bounded).
        coord = self._broadcast_fresh_coordinator()
        if self._inner.last_op_replayed:
            # The coordinator payload was served from the REPLAY cache:
            # this re-formation completed before this incarnation joined
            # (its group may even contain our previous life), so the
            # address is stale — joining it would re-form a backend
            # inside an already-formed group's coordination service.
            # Consume the span's ops (done above, branch-identically)
            # and stay degraded; the next checkpoint boundary runs a
            # FRESH exchange that includes us.  clear_backends above
            # already killed this rank's device arrays — bump the epoch
            # so apps re-upload their resident shards.
            self._log_stderr(
                "re-formation round was replayed (stale group); staying "
                "degraded until the next fresh checkpoint boundary")
            self._drop_distributed_state()
            self._degraded = True
            self._device_epoch += 1
            return
        try:
            self._connect_distributed(coord)
            self._we_initialized_jax = True
            self._build_proc_mesh()
        except Exception as e:  # noqa: BLE001
            if not _is_runtime_failure(e):
                raise
            self._log_stderr(
                f"device-plane re-formation failed ({type(e).__name__}: "
                f"{e}); staying degraded until the next checkpoint")
            self._drop_distributed_state()
            self._degraded = True
            self._device_epoch += 1  # old-epoch arrays died with backends
            return
        self._degraded = False
        self._device_epoch += 1
        if self._obs_on:
            self._metrics.counter("recovery.reforms").inc()
            self._trace.emit("recovery", phase="reform", rank=self._rank,
                             epoch=self._device_epoch)
        self._log_stderr(
            f"device plane re-formed (epoch {self._device_epoch})")

    def _coordinator_host(self) -> str:
        """Interface the other hosts can reach this process on: the one
        that routes to the tracker (works for any inner engine)."""
        from rabit_tpu.utils.net import routable_ip

        return routable_ip(self._tracker_addr)

    def _build_proc_mesh(self) -> None:
        """One device per process, ordered by control-plane rank."""
        import jax
        from jax.sharding import Mesh

        check(jax.process_count() == self._world,
              "XLA engine: JAX world (%d) != tracker world (%d)",
              jax.process_count(), self._world)
        # Mesh positions are ordered by process_index while engine.rank is
        # the control-plane rank — the two must be the same numbering, or
        # allgather rows / broadcast roots would be misattributed.
        check(jax.process_index() == self._rank,
              "XLA engine: jax.process_index() (%d) != control-plane rank "
              "(%d); launch so that process ids match tracker ranks",
              jax.process_index(), self._rank)
        per_proc: dict[int, jax.Device] = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        check(len(per_proc) == self._world,
              "XLA engine: %d processes own devices, expected %d",
              len(per_proc), self._world)
        devs = [per_proc[p] for p in sorted(per_proc)]
        self._proc_mesh = Mesh(np.array(devs), (PROC_AXIS,))

    def _build_proc_mesh_mixed(self) -> None:
        """Mesh build for MIXED mode (tracker + adopted external JAX).

        The two rank spaces are independent here — the platform fixed
        ``jax.process_index()``, the tracker assigned the control-plane
        rank — so a mismatch is a *configuration* state, not a bug, and
        it can differ per rank (e.g. rank 1 of a reversed assignment
        matches itself).  Crashing only the mismatched ranks, or letting
        matched ranks keep the device plane while others degrade, would
        wedge the job in a split-brain collective.  So the verdict is
        agreed by consensus: if ANY rank cannot build the aligned mesh,
        ALL ranks drop it and run degraded on the fault-tolerant host
        transport (and stay there — the engine does not own the external
        runtime, so _maybe_reform is gated off).  The fix is launching
        with matching numberings: tracker-side RABIT_TRACKER_PIN_RANKS=1
        plus the engine's automatic task_id = jax.process_index()
        registration.

        The consensus rides the DEVICE plane (``process_allgather`` is
        rank-order-independent, so it works regardless of alignment),
        NOT the robust host stream: an init-time host op would sit at
        the head of version span 0 on first-life ranks only, breaking
        the span-alignment invariant that lets a worker relaunched
        before the first checkpoint replay against the survivors' cache
        (the same reason the coordinator exchange goes through the
        tracker, _init_jax_distributed).  Only first-start ranks run
        this method — mixed-mode relaunches come up degraded and never
        pair with it — and at first start the external runtime has all
        processes alive by construction (it just formed the JAX world);
        liveness inside that window is the external runtime's, not this
        engine's."""
        import jax

        # Globally-visible mismatches need no collective agreement — and
        # MUST not enter one: with a JAX world larger than the tracker's,
        # the extra processes are still blocked in tracker registration,
        # so a process_allgather would hang the N that got here instead
        # of surfacing the misconfiguration.
        per_proc = {d.process_index for d in jax.devices()}
        if jax.process_count() != self._world \
                or len(per_proc) != self._world:
            self._proc_mesh = None
            self._degraded = True
            self._log_stderr(
                f"MIXED mode: JAX world (processes={jax.process_count()}, "
                f"device-owning={len(per_proc)}) does not match the "
                f"tracker world ({self._world}) — running degraded on "
                "the host transport for the whole job; fix the launch "
                "so the two worlds agree")
            return
        err: Exception | None = None
        try:
            self._build_proc_mesh()
        except Exception as e:  # noqa: BLE001 — consensus decides below
            err = e
        from jax.experimental import multihost_utils

        # A peer flagged as re-registered at ITS first start comes up
        # degraded and never reaches this collective — its first-life
        # peers would then block here (the liveness window belongs to
        # the external runtime that just formed the JAX world).  Bracket
        # the collective with logs so a wedged start is diagnosable from
        # stderr.  Deliberately NOT a unilateral timeout: a rank that
        # times out and degrades while its late allgather still
        # completes on the peers would split the world between degraded
        # and device-plane modes — a permanent divergent hang, strictly
        # worse than this consistent, attributable wait.
        self._log_stderr(
            "MIXED mode: entering init consensus (process_allgather; "
            "if this is the last line, a peer never reached the "
            "collective — check for a degraded relaunch)")
        flags = multihost_utils.process_allgather(
            np.array([0 if err is None else 1], np.int32))
        self._log_stderr("MIXED mode: init consensus complete")
        if not int(np.max(flags)):
            return
        self._proc_mesh = None
        self._degraded = True
        detail = (f" (this rank: {type(err).__name__}: {err})"
                  if err is not None else " (a peer's mesh was misaligned)")
        self._log_stderr(
            "MIXED mode: control-plane ranks and jax.process_index() do "
            "not line up on every rank — running degraded on the host "
            "transport for the whole job" + detail + ".  Launch with "
            "RABIT_TRACKER_PIN_RANKS=1 on the tracker to align them")

    def _control_barrier(self) -> None:
        """Barrier over the host control plane (all ranks must call).
        A failure is logged, never swallowed silently: an unordered
        teardown is exactly the coordination-service race these
        barriers exist to prevent, so it must be diagnosable."""
        try:
            self._inner.allreduce(np.zeros(1, np.uint8), ReduceOp.SUM)
        except Exception as e:  # noqa: BLE001
            self._log_stderr(
                f"control barrier failed ({type(e).__name__}: {e}); "
                "teardown ordering is no longer guaranteed")

    def shutdown(self) -> None:
        if (self._world > 1 and self._inner is not None
                and not self._adopted_jax):
            # Coordination-service teardown is racy once any member died
            # (degradation can be *asymmetric* — a relaunched rank comes
            # up degraded while survivors that issued no device collective
            # since the death are not): a follower whose disconnect RPC
            # lands after the leader (rank 0, coordinator owner) exited is
            # fatally terminated by the error-polling thread.  So ALWAYS
            # order the teardown over our own host control plane:
            # followers disconnect while the leader is provably alive,
            # then the leader follows.  Every rank joins both barriers —
            # including a relaunched incarnation that never joined the
            # JAX group (_we_initialized_jax False).  Like the robust
            # engine's own shutdown consensus (and the reference's
            # pseudo-checkpoint shutdown, allreduce_robust.cc:37-48),
            # these barriers wait for a dead peer's relaunch — under a
            # deployment with no auto-restart, teardown blocks until the
            # link timeout, the same contract as the rest of the robust
            # protocol.
            self._shutdown_distributed_ordered()
        # Ship the device-plane telemetry while the tracker is still up
        # (the inner engine ships its own summary during its shutdown;
        # the tracker merges same-rank summaries section-wise).
        if (self._obs_on and self._world > 1 and self._inner is not None
                and not self._no_host_transport):
            obs.ship_summary(
                self._inner.tracker_print, self._obs_log, "XLAEngine",
                self._rank, self._world, self.stats(),
                [e for e in self._trace.events()
                 if e.get("name") == "recovery"],
                job=self._job_id)
        if self._inner is not None:
            self._inner.shutdown()
        # Overwrite the inner engine's per-rank event dump with the
        # merged trace (device-plane + control-plane, one timeline).
        if self._obs_on and self._obs_dir:
            obs.dump_events(self._obs_log, self._obs_dir, self._rank,
                            self.events())
        self._proc_mesh = None
        self._reduce_cache.clear()

    # ------------------------------------------------------------------
    # identity
    # ------------------------------------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world

    def tracker_print(self, msg: str) -> None:
        self._inner.tracker_print(msg)

    def stats(self) -> dict:
        """Own (device-plane) telemetry; the inner host engine keeps its
        own registry and ships it to the tracker itself.  The raw path
        counters ride along as gauges (``path_stats`` stays available
        unconditionally for tests)."""
        if not self._obs_on or self._metrics is None:
            return {}  # disabled telemetry reports nothing (interface.py)
        self._metrics.gauge("xla.device_ops").set(
            self.path_stats["device_ops"])
        self._metrics.gauge("xla.host_ops").set(self.path_stats["host_ops"])
        self._metrics.gauge("xla.device_epoch").set(self._device_epoch)
        return self._metrics.snapshot()

    def events(self) -> list[dict]:
        own = self._trace.events() if self._trace is not None else []
        inner = self._inner.events() if self._inner is not None else []
        return sorted(own + inner, key=lambda e: e.get("ts", 0.0))

    # ------------------------------------------------------------------
    # data plane
    # ------------------------------------------------------------------
    @property
    def mesh(self):
        """The process-level mesh (None when world==1)."""
        return self._proc_mesh

    def allreduce(
        self,
        buf,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        codec: bool = True,
    ):
        import jax

        if isinstance(buf, np.ndarray):
            if self._no_host_transport and self._world > 1:
                # No host transport in adopt mode — reduce on device and
                # copy back in place (preserving the in-place contract).
                if prepare_fun is not None:
                    prepare_fun()
                out = self._device_collective(
                    jax.numpy.asarray(buf), op, kind="allreduce")
                buf[...] = np.asarray(out)
                return buf
            # Host path: fault-tolerant inner engine (result replay,
            # wire codec honored — the device plane is always exact).
            return self._inner.allreduce(buf, op, prepare_fun, codec)
        check(isinstance(buf, jax.Array),
              "XLA engine: allreduce expects numpy or jax array")
        if prepare_fun is not None:
            prepare_fun()
        if self._world == 1:
            return buf
        if self._degraded:
            return self._host_degrade("allreduce", buf, op)
        try:
            return self._device_collective(buf, op, kind="allreduce")
        except Exception as e:  # noqa: BLE001 — filtered just below
            if not _is_runtime_failure(e):
                raise  # programming error (shape/dtype), not peer failure
            return self._host_degrade("allreduce", buf, op, cause=e)

    def allgather(self, buf):
        import jax

        if isinstance(buf, np.ndarray):
            if self._no_host_transport and self._world > 1:
                out = self._device_collective(
                    jax.numpy.asarray(buf), ReduceOp.SUM, kind="allgather")
                return np.asarray(out)
            return self._inner.allgather(buf)
        if self._world == 1:
            return buf[None]
        if self._degraded:
            return self._host_degrade("allgather", buf, ReduceOp.SUM)
        try:
            return self._device_collective(buf, ReduceOp.SUM,
                                           kind="allgather")
        except Exception as e:  # noqa: BLE001 — filtered just below
            if not _is_runtime_failure(e):
                raise
            return self._host_degrade("allgather", buf, ReduceOp.SUM,
                                      cause=e)

    def allreduce_async(
        self,
        buf,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        fuse: bool = True,
        codec: bool = True,
    ) -> CollectiveHandle:
        """Async passthrough: numpy payloads ride the inner host
        engine's progress thread (overlap + bucket fusion, with the
        robust replay semantics intact); device arrays stay on the
        compiled data plane, which is already asynchronous under JAX
        dispatch, so they resolve synchronously."""
        if (isinstance(buf, np.ndarray) and self._world > 1
                and self._inner is not None
                and not self._no_host_transport and not self._degraded):
            return self._inner.allreduce_async(buf, op, prepare_fun,
                                               fuse=fuse, codec=codec)
        return CollectiveHandle.resolved(
            self.allreduce(buf, op, prepare_fun, codec))

    def allgather_async(self, buf) -> CollectiveHandle:
        if (isinstance(buf, np.ndarray) and self._world > 1
                and self._inner is not None
                and not self._no_host_transport and not self._degraded):
            return self._inner.allgather_async(buf)
        return CollectiveHandle.resolved(self.allgather(buf))

    def _host_degrade(self, kind: str, buf, op: ReduceOp,
                      cause: Exception | None = None):
        """Degraded mode: the device collective failed (typically a peer
        died mid-program, which XLA cannot recover from).  Route the
        payload through the inner fault-tolerant host engine — its
        consensus/recovery protocol re-forms the world (reference
        recovery path: src/allreduce_robust.cc:426-453) — and return a
        device array so callers keep their types.  Bulk ops ride the
        host path until the next checkpoint boundary re-forms the
        device plane (_maybe_reform; or, with rabit_device_reform=0,
        until the job is relaunched whole)."""
        import jax.numpy as jnp

        if self._inner is None or self._no_host_transport:
            raise RuntimeError(
                "XLA engine: device collective failed and no host "
                "transport is available (adopt mode)") from cause
        if not self._degraded:
            self._degraded = True
            import sys

            print("[rabit_tpu] xla engine: device collective failed "
                  f"({type(cause).__name__}: {cause}); degrading to host "
                  "transport", file=sys.stderr, flush=True)
            if self._obs_on:
                self._metrics.counter("recovery.degrades").inc()
                self._trace.emit("recovery", phase="degrade",
                                 rank=self._rank, kind=kind,
                                 epoch=self._device_epoch)
        host = np.asarray(buf)
        if kind == "allreduce":
            out = self._inner.allreduce(host.copy(), op)
        else:
            out = self._inner.allgather(host)
        self.path_stats["host_ops"] += 1
        if self._obs_on:
            self._metrics.counter("op.host_degraded.count").inc()
            self._metrics.counter("op.host_degraded.bytes").inc(host.nbytes)
        return jnp.asarray(out)

    def _device_collective(self, arr, op: ReduceOp, kind: str):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if not arr.is_fully_addressable:
            # Output of a previous engine collective: a global array
            # replicated across processes — peel off the local replica.
            check(arr.is_fully_replicated,
                  "XLA engine: global input arrays must be fully replicated")
            arr = arr.addressable_shards[0].data
        local = jax.device_put(arr, jax.local_devices()[0])[None]
        global_shape = (self._world,) + tuple(arr.shape)
        garr = jax.make_array_from_single_device_arrays(
            global_shape,
            NamedSharding(self._proc_mesh, P(PROC_AXIS)),
            [local],
        )
        fn = self._collective_fn(kind, tuple(arr.shape),
                                 np.dtype(arr.dtype).name, ReduceOp(op))
        t0 = time.perf_counter() if self._obs_on else 0.0
        out = fn(garr)
        self.path_stats["device_ops"] += 1
        if self._obs_on:
            # dispatch time only: device collectives are asynchronous and
            # blocking here to time them would serialize the data plane
            dt = time.perf_counter() - t0
            self._metrics.counter(f"op.device_{kind}.count").inc()
            self._metrics.counter(f"op.device_{kind}.bytes").inc(arr.nbytes)
            self._metrics.histogram(
                f"op.device_{kind}.dispatch_seconds").observe(dt)
            self._trace.emit("op", kind=f"device_{kind}",
                             nbytes=int(arr.nbytes), dur=dt,
                             rank=self._rank)
        return out

    def _use_pallas_ring(self, shape, dtype_name: str, op: ReduceOp) -> bool:
        """pallas_ring serves large {SUM,MAX,MIN,PROD} allreduces; small
        payloads and other ops stay on psum (latency-bound territory —
        the ring's 2(N-1) hops only pay off once bandwidth dominates).

        Off-TPU the kernel runs in interpret mode, whose simulated
        remote DMAs live inside one process: a multi-process CPU mesh
        (the CI harness) must stay on psum or the collective wedges, so
        the ring engages only on real TPU backends or single-process
        meshes (where tests and the driver's dryrun exercise it)."""
        if self._device_impl != "pallas_ring":
            return False
        import jax

        if jax.default_backend() != "tpu" and jax.process_count() > 1:
            return False
        from rabit_tpu.ops.ring_allreduce import supported_ops

        if op not in supported_ops():
            return False
        nbytes = int(np.prod(shape, dtype=np.int64)) * \
            np.dtype(dtype_name).itemsize
        return nbytes >= self._pallas_min_bytes

    def _collective_fn(self, kind: str, shape, dtype_name: str, op: ReduceOp):
        key = (kind, shape, dtype_name, op)
        fn = self._reduce_cache.get(key)
        if fn is None:
            from jax import lax
            from jax.sharding import PartitionSpec as P

            from rabit_tpu.parallel import collectives as C

            nd = len(shape)
            check_vma = True
            if kind == "allreduce" and self._use_pallas_ring(
                    shape, dtype_name, op):
                from rabit_tpu.ops.ring_allreduce import \
                    ring_allreduce_pallas

                body = lambda s: ring_allreduce_pallas(  # noqa: E731
                    s[0], PROC_AXIS, op)
                out_spec = P(*([None] * nd))
                # pallas outputs carry no varying-across-mesh annotation;
                # the static replication check cannot see through them
                check_vma = False
            elif kind == "allreduce":
                body = lambda s: C.allreduce(s[0], PROC_AXIS, op)  # noqa: E731
                out_spec = P(*([None] * nd))
            else:
                # allgather: (world, *shape) replicated everywhere.
                # Expressed as scatter-into-zeros + psum rather than
                # lax.all_gather so shard_map can statically prove the
                # output replicated (all_gather's output defeats the VMA
                # replication check).
                import jax.numpy as jnp

                world = self._world

                def body(s, world=world):  # noqa: E731
                    buf = jnp.zeros((world,) + tuple(s[0].shape),
                                    s[0].dtype)
                    buf = lax.dynamic_update_index_in_dim(
                        buf, s[0], lax.axis_index(PROC_AXIS), 0)
                    return lax.psum(buf, PROC_AXIS)

                out_spec = P(*([None] * (nd + 1)))
            fn = C.shard_collective(
                self._proc_mesh, body,
                in_specs=(P(PROC_AXIS, *([None] * nd)),),
                out_specs=out_spec, check_vma=check_vma)
            self._reduce_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # control plane delegation
    # ------------------------------------------------------------------
    def broadcast(self, data: Optional[bytes], root: int) -> bytes:
        if self._no_host_transport and self._world > 1:
            # No host transport in adopt mode — ship bytes over the device
            # collectives (length first, then a pow2-padded payload so the
            # compile cache stays logarithmic in payload size).
            return self._device_byte_broadcast(data, root)
        return self._inner.broadcast(data, root)

    def _device_byte_broadcast(self, data: Optional[bytes], root: int) -> bytes:
        import jax.numpy as jnp

        is_root = self._rank == root
        check(not is_root or data is not None,
              "broadcast: root rank must supply data")
        n = jnp.asarray(
            np.array([len(data) if is_root else 0], np.int32))
        total = int(np.asarray(
            self._device_collective(n, ReduceOp.SUM, "allreduce"))[0])
        padded = max(1, 1 << (total - 1).bit_length()) if total else 1
        buf = np.zeros(padded, np.uint8)
        if is_root:
            buf[:total] = np.frombuffer(data, np.uint8)
        out = self._device_collective(
            jnp.asarray(buf), ReduceOp.SUM, "allreduce")
        return np.asarray(out)[:total].tobytes()

    def load_checkpoint(self):
        out = self._inner.load_checkpoint()
        # Same consensus exchange as checkpoint(), for the same span:
        # a relaunched rank resumes at version v exactly where survivors
        # committed v, so both issue the flags op as the FIRST inner op
        # of span v and the robust replay streams stay aligned.  (At a
        # healthy start every rank does this once at version 0.)
        self._maybe_reform()
        return out

    def checkpoint(self, global_model, local_model=None, lazy_global=None):
        self._inner.checkpoint(global_model, local_model, lazy_global)
        # The committed checkpoint is the all-ranks consensus boundary:
        # heal a degraded device plane here (reference recovered jobs
        # return to full speed the same way, src/allreduce_robust.cc:
        # 426-453).  The flags exchange runs AFTER the commit — the
        # FIRST inner op of the new version span — because a relaunched
        # rank re-enters through load_checkpoint at exactly that span
        # boundary and issues the same flags op first (load_checkpoint
        # below), keeping the robust replay streams aligned.  Committing
        # first also means survivors are never blocked pre-commit by a
        # dead peer: the relaunch then resumes at the NEW version and
        # skips the iteration whose device-plane results only the
        # survivors hold.
        self._maybe_reform()

    @property
    def version_number(self) -> int:
        return self._inner.version_number
