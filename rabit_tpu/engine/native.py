"""ctypes binding to the native C++ engine (librabit_tpu.so).

TPU-native equivalent of the reference's Python wrapper
(reference: wrapper/rabit.py:54-306 loading librabit_wrapper*.so via
ctypes).  One shared library serves every variant; the variant is chosen
at Init time via the ``rabit_engine`` parameter (base | robust | mock)
rather than by loading a differently-built .so.
"""
from __future__ import annotations

import ctypes
import os
import time
from typing import Callable, Optional

import numpy as np

from rabit_tpu import obs
from rabit_tpu.engine.interface import Engine
from rabit_tpu.ops import ReduceOp
from rabit_tpu.ops.reduce_ops import dtype_to_enum
from rabit_tpu.utils.checks import check, error

_LIB_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "native", "lib",
                 "librabit_tpu.so"),
    "librabit_tpu.so",
]

_PREPARE_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_REDUCER_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_size_t, ctypes.c_void_p)
_SERIALIZE_CB = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_void_p)


def _load_lib() -> ctypes.CDLL:
    last = None
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(path)
                              if os.path.sep in path else path)
            break
        except OSError as e:
            last = e
    else:
        raise ImportError(f"librabit_tpu.so not found "
                          f"(build with make -C rabit_tpu/native): {last}")
    lib.RbtTpuInit.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_char_p)]
    lib.RbtTpuGetLastError.restype = ctypes.c_char_p
    lib.RbtTpuDebugRoutedBytes.restype = ctypes.c_ulonglong
    lib.RbtTpuDebugScratchPeakBytes.restype = ctypes.c_ulonglong
    lib.RbtTpuGetProcessorName.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.RbtTpuTrackerPrint.argtypes = [ctypes.c_char_p]
    lib.RbtTpuAllreduce.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
        _PREPARE_CB, ctypes.c_void_p]
    lib.RbtTpuAllreduceCustom.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
        _REDUCER_CB, ctypes.c_void_p, _PREPARE_CB, ctypes.c_void_p]
    lib.RbtTpuBroadcastBlob.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t)]
    lib.RbtTpuAllgather.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p]
    lib.RbtTpuLoadCheckPoint.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t)]
    lib.RbtTpuCheckPoint.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t]
    lib.RbtTpuLazyCheckPoint.argtypes = [
        _SERIALIZE_CB, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    return lib


_lib: Optional[ctypes.CDLL] = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


def native_available() -> bool:
    try:
        _get_lib()
        return True
    except ImportError:
        return False


class NativeEngine(Engine):
    """Python face of the C++ engine."""

    def __init__(self, variant: str = "base"):
        self._variant = variant
        self._lib = _get_lib()
        # Keep a live reference to the lazily-stashed local model for the
        # lazy_checkpoint contract (serialization stays Python-side).
        self._shutdown_done = False
        # Telemetry: the C++ engine is opaque, so ops are timed/counted
        # at this binding layer (doc/observability.md).
        self._obs_on = False
        self._obs_dir: Optional[str] = None
        self._metrics: Optional[obs.Metrics] = None
        self._trace: Optional[obs.EventTrace] = None
        self._log = obs.log.Logger("native", lambda: {"rank": self.rank})

    def _raise_last(self, what: str):
        msg = self._lib.RbtTpuGetLastError().decode("utf-8", "replace")
        error("%s failed: %s", what, msg)

    def init(self, params: dict) -> None:
        args = [f"rabit_engine={self._variant}"]
        for key, val in params.items():
            if key.startswith("rabit_") or key.startswith("mock"):
                args.append(f"{key}={val}")
        argv = (ctypes.c_char_p * len(args))(
            *[a.encode("utf-8") for a in args])
        cfg = obs.configure(params)
        self._obs_on = cfg.enabled
        self._obs_dir = cfg.obs_dir
        self._metrics = obs.Metrics()
        self._trace = obs.EventTrace(capacity=cfg.trace_capacity)
        if self._lib.RbtTpuInit(len(args), argv) != 0:
            self._raise_last("init")

    def shutdown(self) -> None:
        if not self._shutdown_done:
            self._obs_flush()
            self._lib.RbtTpuFinalize()
            self._shutdown_done = True

    # ------------------------------------------------------------------
    # telemetry (rabit_tpu.obs) — binding-layer instrumentation
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        if not self._obs_on or self._metrics is None:
            return {}  # disabled telemetry reports nothing (interface.py)
        # Native debug counters surfaced as gauges so they aggregate
        # like everything else.
        try:
            self._metrics.gauge("native.routed_bytes").set(
                self.debug_routed_bytes())
            self._metrics.gauge("native.scratch_peak_bytes").set(
                self.debug_scratch_peak_bytes())
        except (OSError, AttributeError):  # pragma: no cover
            pass
        return self._metrics.snapshot()

    def events(self) -> list[dict]:
        return self._trace.events() if self._trace is not None else []

    def _op_done(self, kind: str, nbytes: int, t0: float) -> None:
        obs.record_op(self._metrics, self._trace, kind, nbytes,
                      time.perf_counter() - t0, self.rank,
                      replayed=bool(self.last_op_replayed))

    def _obs_flush(self) -> None:
        """Ship the rank summary over the tracker print channel and dump
        the event trace — same contract as the Python engines."""
        if not self._obs_on:
            return
        rank, world = self.rank, self.world_size
        if world > 1:
            obs.ship_summary(
                self.tracker_print, self._log, type(self).__name__,
                rank, world, self.stats(),
                [e for e in self._trace.events() if e.get("name") != "op"])
        if self._obs_dir:
            obs.dump_events(self._log, self._obs_dir, rank,
                            self._trace.events())

    @property
    def rank(self) -> int:
        return self._lib.RbtTpuGetRank()

    @property
    def world_size(self) -> int:
        return self._lib.RbtTpuGetWorldSize()

    @property
    def host(self) -> str:
        buf = ctypes.create_string_buffer(256)
        self._lib.RbtTpuGetProcessorName(buf, 256)
        return buf.value.decode("utf-8", "replace")

    def tracker_print(self, msg: str) -> None:
        if self._lib.RbtTpuTrackerPrint(msg.encode("utf-8")) != 0:
            self._raise_last("tracker_print")

    def allreduce(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        codec: bool = True,
    ) -> np.ndarray:
        # ``codec`` accepted for interface parity; the native wire has
        # no Python-side codec layer (full-width bytes always).
        check(isinstance(buf, np.ndarray),
              "native engine: device arrays route via the xla engine")
        cb = _PREPARE_CB()
        if prepare_fun is not None:
            cb = _PREPARE_CB(lambda _arg: prepare_fun())
        t0 = time.perf_counter() if self._obs_on else 0.0
        rc = self._lib.RbtTpuAllreduce(
            buf.ctypes.data_as(ctypes.c_void_p), buf.size,
            int(dtype_to_enum(buf.dtype)), int(op), cb, None)
        if rc != 0:
            self._raise_last("allreduce")
        if self._obs_on:
            self._op_done("allreduce", buf.nbytes, t0)
        return buf

    def allreduce_custom(
        self,
        buf: np.ndarray,
        reducer: Callable[[np.ndarray, np.ndarray], None],
        prepare_fun: Optional[Callable[[], None]] = None,
    ) -> np.ndarray:
        """Custom reduction through the native robust path: the C++
        engine runs the tree/recovery protocol and calls back into the
        Python ``reducer(dst, src)`` with numpy views per merge
        (reference: ReduceHandle, include/rabit/engine.h:215-253 —
        the reference never exposed this to Python)."""
        check(isinstance(buf, np.ndarray),
              "native engine: allreduce_custom expects a numpy array")
        count = buf.shape[0] if buf.ndim > 0 else buf.size
        check(count > 0, "allreduce_custom: empty buffer")
        item_size = buf.nbytes // count  # bytes per axis-0 row
        shape_tail = buf.shape[1:] if buf.ndim > 1 else ()

        # ctypes swallows exceptions raised inside callbacks (it prints
        # and returns normally) — capture the first one and re-raise
        # after the collective so the caller never sees unmerged data
        # reported as success.
        failure: list[BaseException] = []

        def c_reducer(dst_p, src_p, n, _arg):
            if failure:
                return  # already failed; don't cascade
            try:
                n = int(n)
                dst = np.ctypeslib.as_array(
                    ctypes.cast(dst_p, ctypes.POINTER(ctypes.c_uint8)),
                    shape=(n * item_size,)).view(buf.dtype
                                                 ).reshape((n,) + shape_tail)
                src = np.ctypeslib.as_array(
                    ctypes.cast(src_p, ctypes.POINTER(ctypes.c_uint8)),
                    shape=(n * item_size,)).view(buf.dtype
                                                 ).reshape((n,) + shape_tail)
                reducer(dst, src)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                failure.append(e)

        rcb = _REDUCER_CB(c_reducer)
        pcb = _PREPARE_CB()
        if prepare_fun is not None:
            pcb = _PREPARE_CB(lambda _arg: prepare_fun())
        t0 = time.perf_counter() if self._obs_on else 0.0
        rc = self._lib.RbtTpuAllreduceCustom(
            buf.ctypes.data_as(ctypes.c_void_p), count, item_size,
            rcb, None, pcb, None)
        if failure:
            raise RuntimeError(
                "allreduce_custom: reducer raised during the collective; "
                "results on all ranks are unusable") from failure[0]
        if rc != 0:
            self._raise_last("allreduce_custom")
        if self._obs_on:
            self._op_done("allreduce_custom", buf.nbytes, t0)
        return buf

    def broadcast(self, data: Optional[bytes], root: int) -> bytes:
        payload = data if data is not None else b""
        t0 = time.perf_counter() if self._obs_on else 0.0
        out = ctypes.c_char_p()
        out_len = ctypes.c_size_t()
        rc = self._lib.RbtTpuBroadcastBlob(
            payload, len(payload), root,
            ctypes.byref(out), ctypes.byref(out_len))
        if rc != 0:
            self._raise_last("broadcast")
        result = ctypes.string_at(out, out_len.value)
        if self._obs_on:
            self._op_done("broadcast", len(result), t0)
        return result

    def allgather(self, buf: np.ndarray) -> np.ndarray:
        world = self.world_size
        t0 = time.perf_counter() if self._obs_on else 0.0
        out = np.empty((world,) + buf.shape, dtype=buf.dtype)
        rc = self._lib.RbtTpuAllgather(
            buf.ctypes.data_as(ctypes.c_void_p), buf.nbytes,
            out.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            self._raise_last("allgather")
        if self._obs_on:
            self._op_done("allgather", out.nbytes, t0)
        return out

    def load_checkpoint(self):
        gptr = ctypes.c_char_p()
        glen = ctypes.c_size_t()
        lptr = ctypes.c_char_p()
        llen = ctypes.c_size_t()
        version = self._lib.RbtTpuLoadCheckPoint(
            ctypes.byref(gptr), ctypes.byref(glen),
            ctypes.byref(lptr), ctypes.byref(llen))
        if version < 0:
            self._raise_last("load_checkpoint")
        if version == 0:
            return (0, None, None)
        g = ctypes.string_at(gptr, glen.value) if glen.value else None
        l = ctypes.string_at(lptr, llen.value) if llen.value else None
        return (version, g, l)

    def checkpoint(self, global_model, local_model=None, lazy_global=None):
        if global_model is None and lazy_global is not None:
            return self._lazy_checkpoint(lazy_global, local_model)
        g = global_model or b""
        # NOTE: the previous lazy callback must stay alive THROUGH this
        # native call — CheckPointImpl can run RecoverExec ->
        # ServeCheckpointLoad -> MaterializeGlobal (a rank rejoining
        # mid-checkpoint) before CommitCheckPoint swaps the model, which
        # invokes the old trampoline.  Clear it only after return.
        if local_model is not None:
            rc = self._lib.RbtTpuCheckPoint(g, len(g), local_model,
                                            len(local_model))
        else:
            rc = self._lib.RbtTpuCheckPoint(g, len(g), None, 0)
        if rc != 0:
            # keep the old trampoline: a failed barrier leaves the C++
            # lazy_global_ untouched and it may still be invoked later
            self._raise_last("checkpoint")
        self._lazy_cb = None  # a real checkpoint supersedes any lazy fn

    def _lazy_checkpoint(self, lazy_global, local_model) -> None:
        """True LazyCheckPoint: the C++ engine calls back for the bytes
        only when a peer (or a local load) needs them — zero
        serialization cost in the steady state (reference:
        src/allreduce_robust.cc:744-751)."""

        def c_serialize(len_out, _arg):
            # keep the payload alive on self: the C++ side copies it
            # during this call, but ctypes needs the pointer valid on
            # return
            self._lazy_payload = lazy_global()
            ctypes.cast(len_out, ctypes.POINTER(ctypes.c_size_t)
                        )[0] = len(self._lazy_payload)
            return ctypes.cast(ctypes.c_char_p(self._lazy_payload),
                               ctypes.c_void_p).value

        # the callback must outlive this call: the engine may invoke it
        # during any later collective's recovery, until the next
        # checkpoint.  The PREVIOUS callback must also survive until the
        # native call returns — recovery during LazyCheckPoint can still
        # materialize the old version's model — so keep self._lazy_cb
        # bound to it and swap in the new trampoline only afterwards.
        cb = _SERIALIZE_CB(c_serialize)
        if local_model is not None:
            rc = self._lib.RbtTpuLazyCheckPoint(cb, None,
                                                local_model,
                                                len(local_model))
        else:
            rc = self._lib.RbtTpuLazyCheckPoint(cb, None,
                                                None, 0)
        if rc != 0:
            # keep the OLD trampoline referenced: on failure the C++
            # engine may still hold the previous lazy_global_
            self._raise_last("lazy_checkpoint")
        self._lazy_cb = cb

    @property
    def version_number(self) -> int:
        return self._lib.RbtTpuVersionNumber()

    def debug_routed_bytes(self) -> int:
        """Payload bytes this rank has sent through the requester-routed
        recovery broadcast (tests assert recovery traffic scales with
        requesters, not world size)."""
        return int(self._lib.RbtTpuDebugRoutedBytes())

    def debug_scratch_peak_bytes(self) -> int:
        """Largest per-op collective scratch allocation so far (tests
        assert it stays within the rabit_reduce_buffer budget)."""
        return int(self._lib.RbtTpuDebugScratchPeakBytes())

    @property
    def was_relaunched(self) -> bool:
        return bool(self._lib.RbtTpuWasRelaunched())

    @property
    def last_op_replayed(self) -> bool:
        return bool(self._lib.RbtTpuLastReplayed())
