"""Engine layer: the pluggable collective backends.

TPU-native equivalent of the reference's engine selection layer
(reference: src/engine.cc:20-48 — a compile-time singleton choosing between
base/robust/mock/empty/MPI library variants).  We select at *runtime* by
name instead: ``empty`` (world=1 no-op), ``pysocket`` (pure-Python TCP,
non-fault-tolerant), ``pyrobust`` (pure-Python TCP with the full
cache/replay recovery protocol — no compiled library needed), ``native``
(C++ TCP engine, robust by default; ``base`` selects the
non-fault-tolerant variant), ``mock`` (native engine with fault-injection
kill points), ``xla`` (JAX/XLA collectives over the device mesh) and
``mpi`` (mpi4py, when installed).
"""
from __future__ import annotations

from rabit_tpu.engine.interface import Engine
from rabit_tpu.utils.checks import check

_engine: Engine | None = None


def _make_engine(name: str, params: dict) -> Engine:
    if name == "empty":
        from rabit_tpu.engine.empty import EmptyEngine

        return EmptyEngine()
    if name == "pysocket":
        from rabit_tpu.engine.pysocket import PySocketEngine

        return PySocketEngine()
    if name == "pyrobust":
        from rabit_tpu.engine.robust import PyRobustEngine

        return PyRobustEngine()
    if name in ("native", "base", "robust", "mock"):
        try:
            from rabit_tpu.engine.native import NativeEngine
        except ImportError as e:
            raise RuntimeError(
                f"engine {name!r} needs the native library "
                "(make -C rabit_tpu/native)") from e

        # "native" defaults to the fault-tolerant robust variant.
        return NativeEngine(variant=name if name != "native" else "robust")
    if name == "xla":
        from rabit_tpu.engine.xla import XLAEngine

        return XLAEngine()
    if name == "mpi":
        from rabit_tpu.engine.mpi import MPIEngine

        return MPIEngine()
    raise ValueError(f"unknown engine: {name!r}")


def init(params: dict | None = None) -> Engine:
    """Create and initialise the global engine singleton.

    Reference: engine::Init (src/engine.cc:31-39) — parses name=value
    parameters and forwards them to the engine's SetParam.
    """
    global _engine
    check(_engine is None, "engine already initialised; call finalize() first")
    params = dict(params or {})
    name = params.pop("rabit_engine", None) or _autodetect(params)
    eng = _make_engine(name, params)
    eng.init(params)
    _engine = eng
    return eng


def _autodetect(params: dict) -> str:
    """Pick an engine: tracker configured → native (falling back to the
    pure-Python robust engine when the library isn't built, so fault
    tolerance never silently disappears with the ``.so``), else empty."""
    import os

    if "rabit_tracker_uri" in params or "RABIT_TRACKER_URI" in os.environ:
        try:
            from rabit_tpu.engine.native import native_available

            if native_available():
                return "native"
        except ImportError:
            pass
        return "pyrobust"
    return "empty"


def get_engine() -> Engine:
    check(_engine is not None, "rabit_tpu is not initialised; call init() first")
    return _engine


def initialized() -> bool:
    return _engine is not None


def is_device_plane() -> bool:
    """True when the active engine reduces ``jax.Array`` payloads over
    the device data plane (the XLA engine in a multi-process world) —
    apps keep such payloads on device instead of converting to numpy."""
    if _engine is None or not _engine.is_distributed():
        return False
    try:
        from rabit_tpu.engine.xla import XLAEngine

        return isinstance(_engine, XLAEngine)
    except ImportError:  # pragma: no cover
        return False


def finalize() -> None:
    global _engine
    if _engine is not None:
        _engine.shutdown()
        _engine = None
