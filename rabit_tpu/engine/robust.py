"""Pure-Python fault-tolerant engine: cache/replay recovery over pysocket.

TPU-native rebuild of the reference robust engine **without the native
library** (reference: src/allreduce_robust.{h,cc}; native sibling:
native/src/robust_engine.cc — this file mirrors its redesigned protocol
so the two implementations stay behaviourally interchangeable).  It
layers on :class:`PySocketEngine`'s links and collectives, so every
environment that can run the portable TCP engine — TPU VMs on the
pysocket/XLA host fallback, the tier-1 CPU CI, laptops without a C++
toolchain — gets the paper's headline feature: a crashed worker rejoins
the running job and catches up from in-memory checkpoints instead of
restarting the world.

Protocol (same shape as the native engine):

* Every collective first runs a tiny **consensus allreduce** over the
  tree links carrying ``(flags, seqno, version, op-fingerprint)``.
  Uniform ``(version, seqno)`` with no flags set means "everyone is
  here: execute for real"; a lagging seqno means a relaunched rank needs
  the cached result of ``min(seqno)`` **replayed** (its ``prepare_fun``
  is skipped and ``last_op_replayed`` is True); a lagging version means
  a checkpoint commit must catch up.  The fingerprint is a pure-Python
  extension: it hashes the op type, reduce op/dtype and payload size, so
  ranks that disagree on the op at a uniform ``(version, seqno)`` fail
  loudly at the consensus round instead of corrupting payloads
  downstream.  (A rank that simply calls *more* collectives than its
  peers before ``shutdown()`` is outside this net, same as the native
  engine.)
* Results are cached by seqno within the current version span, with the
  native engine's **striped replication** (``rabit_global_replica``)
  bounding memory; the cache is cleared at every checkpoint commit.
* ``checkpoint()`` commits the global model on every rank (world-wide
  replication — strictly stronger than the tree-neighbor minimum) and
  ring-replicates each rank's **local** model to its
  ``rabit_local_replica`` ring successors; recovery floods the blobs
  backward so a dead rank's own state survives its death.
* With ``rabit_ckpt_dir`` set, elected writer ranks additionally
  persist every committed version to the **durable tier**
  (:mod:`rabit_tpu.ckpt`: atomic CRC-stamped blobs + manifest), and the
  checkpoint-load path cold-resumes from the newest valid on-disk
  version when *no* live rank holds one — kill-all-ranks restarts
  resume at the last committed version instead of 0, and a rejoiner
  whose disk outran the cluster raises the typed
  :class:`~rabit_tpu.ckpt.CheckpointSkewError`.
* Any :class:`LinkError` cascades every survivor into a tracker
  ``recover`` rendezvous (the tracker serves full-world recover rounds);
  the relaunched rank registers with ``start``, loads the checkpoint
  from the agreed newest holder, replays cached results, and rejoins the
  op it died in mid-flight.
* ``RABIT_MOCK`` kill-points — ``rank,version,seqno,ndeath`` tuples,
  ``;``-separated, seqno ``1<<20`` = at checkpoint, ``(1<<20)+1`` = at
  load — drive deterministic fault injection exactly like the native
  mock engine (exit 254 → the keepalive launcher restarts with an
  incremented ``RABIT_NUM_TRIAL``).

Differences from the native robust engine, on purpose:

* Recovery payloads ride the plain tree flood from the agreed root
  (everyone receives) instead of the requester-routed broadcast; the
  O(tree-path) traffic bound is a native-only optimisation, asserted by
  a native-only test.
* No retired-buffer pool: numpy/bytes allocation is not the Python
  path's bottleneck.
"""
from __future__ import annotations

import os
import struct
import time
import zlib
from typing import Callable, Optional

import numpy as np

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu import obs
from rabit_tpu import sched as sched_mod
from rabit_tpu.engine.pysocket import (LinkError, PySocketEngine,
                                       WorldChangedError)
from rabit_tpu.ops import ReduceOp
from rabit_tpu.tracker import protocol as P
from rabit_tpu.utils.checks import RabitError, check, error


class RecoveryError(RabitError):
    """Recover rendezvous exhausted its bounded attempt budget.

    Raised when the post-failure re-rendezvous cannot be completed
    within ``rabit_recover_attempts`` tries (each already including the
    connect retry/backoff schedule) or the barrier deadline — the job's
    control plane is unreachable and a supervisor must restart the
    world.  ``history`` carries one ``(attempt, monotonic_ts, error)``
    triple per failed attempt, so the failure narrative survives into
    logs and postmortems instead of vanishing into a spin loop."""

    def __init__(self, msg: str,
                 history: list[tuple[int, float, str]]) -> None:
        super().__init__(msg)
        self.history = list(history)

# Consensus flags (same values as the native engine's enum,
# native/include/rabit_tpu/robust_engine.h; reference analogue:
# src/allreduce_robust.h:163-235).
K_LOAD_CHECK = 1    # a (re)started rank wants the latest checkpoint
K_CHECKPOINT = 2    # at the checkpoint barrier
K_CHECK_ACK = 4     # committed, waiting for everyone to commit
K_SHUTDOWN = 8      # finished the program, serving stragglers
K_DIFF_SEQ = 16     # derived: seqnos differ -> serve min
K_DIFF_VERSION = 32  # derived: versions differ -> commit catch-up
K_LOCAL_CHK = 64    # this checkpoint carries a local model
# Python-only extension: op fingerprints differ at a uniform
# (version, seqno) — the collective call sequences diverged.
K_DIFF_OP = 128
# Python-only extension (elastic membership): set alongside K_CHECK_ACK
# by any rank whose commit-boundary tracker poll saw a pending rescale
# epoch.  The OR-merge makes the decision uniform — if ANY rank saw it,
# every rank's ack round agrees on it and the whole world enters the
# cmd=rescale re-rendezvous together, exactly at the commit boundary.
# Riding the existing consensus word (instead of a separate agreement
# op) means a concurrently-(re)joining loader interoperates for free.
K_RESCALE = 256

# Sentinel seqnos for kill-points at non-collective calls (same
# encoding as the native mock engine and tests/test_recovery.py).
SEQ_CHECKPOINT = 1 << 20
SEQ_LOAD_CHECK = SEQ_CHECKPOINT + 1

_WORD_BYTES = 16  # flags, seq, version, fingerprint — all u32


class PyRobustEngine(PySocketEngine):
    """Fault-tolerant engine over the pure-Python TCP transport.

    Select with ``rabit_engine=pyrobust``.  Drop-in for the native
    ``robust``/``mock`` variants: same checkpoint/replay semantics, same
    ``RABIT_MOCK`` fault-injection format, no compiled library needed.
    """

    def __init__(self) -> None:
        super().__init__()
        self._seq = 0
        self._cache: dict[int, bytes] = {}  # seqno -> result (this version)
        self._num_global_replica = 5
        self._num_local_replica = 2
        self._recover_attempts = 8  # rabit_recover_attempts
        self._last_replayed = False
        self._has_checkpoint = False
        self._lazy_global: Optional[Callable[[], bytes]] = None
        # Pending checkpoint state between barrier and commit.
        self._pending_global = b""
        self._pending_lazy: Optional[Callable[[], bytes]] = None
        self._pending_local = b""
        self._has_pending_local = False
        # origin rank -> (version, blob) for ring-replicated local models.
        self._local_store: dict[int, tuple[int, bytes]] = {}
        # Mock fault injection: {(version, seqno, ndeath)} for THIS rank.
        self._kill_points: set[tuple[int, int, int]] = set()
        self._num_trial = 0
        # Durable checkpoint tier (rabit_ckpt_dir): None = disabled.
        self._ckpt_store: Optional[ckpt_mod.CheckpointStore] = None
        self._ckpt_writers = 0
        self._ckpt_dir_raw = ""   # unexpanded: re-elected after rescale
        self._ckpt_keep = 3
        # Elastic membership (rabit_elastic): poll the tracker at every
        # commit boundary and re-rendezvous when an epoch is pending.
        self._elastic = False
        # Online adaptation (rabit_adapt): ALSO poll at commit
        # boundaries, so the tracker's AdaptiveController can push
        # schedule-switch epochs (same K_RESCALE choreography at an
        # unchanged world) without elastic membership armed.
        self._adapt = False
        # Agreed flags of the most recent consensus round — how the
        # commit path learns whether any rank's poll saw K_RESCALE.
        self._last_agreed = 0
        # True between a LinkError and the consensus round that realigns
        # the world — drives the "resume" telemetry event.
        self._recovering = False
        self._log = obs.log.Logger("pyrobust", self._log_ctx)

    def _obs_role(self) -> str:
        return "pyrobust"

    def _log_ctx(self) -> dict:
        """Rank/version/seqno prefix — plus the tenant name, so merged
        stderr from co-tenant jobs stays attributable."""
        ctx = super()._log_ctx()
        ctx["v"] = self._version
        ctx["seq"] = self._seq
        return ctx

    def _op_seqno(self) -> Optional[int]:
        return self._seq

    def _emit_phase(self, phase: str, **fields) -> None:
        """One recovery-protocol event (call sites gate on _obs_on).
        Mirrored into the flight recorder's ring: recovery phases are
        exactly the "last seconds" evidence a postmortem wants."""
        fields.setdefault("seqno", self._seq)
        fields.setdefault("version", self._version)
        self._trace.emit("recovery", phase=phase, rank=self._rank, **fields)
        if self._flight is not None:
            self._flight.note("recovery", phase=phase, rank=self._rank,
                              **fields)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def init(self, params: dict) -> None:
        self._num_global_replica = int(
            params.get("rabit_global_replica")
            or os.environ.get("RABIT_GLOBAL_REPLICA", 5))
        self._num_local_replica = int(
            params.get("rabit_local_replica")
            or os.environ.get("RABIT_LOCAL_REPLICA", 2))
        check(self._num_global_replica > 0, "rabit_global_replica must be >= 1")
        check(self._num_local_replica > 0, "rabit_local_replica must be >= 1")
        self._recover_attempts = int(
            params.get("rabit_recover_attempts")
            or os.environ.get("RABIT_RECOVER_ATTEMPTS", 8))
        check(self._recover_attempts > 0,
              "rabit_recover_attempts must be >= 1")
        ckpt_dir = str(params.get("rabit_ckpt_dir")
                       or os.environ.get("RABIT_CKPT_DIR", "")).strip()
        # `x or env` would silently turn an explicit (invalid) 0 into
        # the default instead of failing the >= 1 check below.
        keep_raw = params.get("rabit_ckpt_keep")
        if keep_raw in (None, ""):
            keep_raw = os.environ.get("RABIT_CKPT_KEEP", 3)
        ckpt_keep = int(keep_raw)
        writers_raw = params.get("rabit_ckpt_writers")
        if writers_raw in (None, ""):
            writers_raw = os.environ.get("RABIT_CKPT_WRITERS", "")
        self._elastic = str(
            params.get("rabit_elastic")
            or os.environ.get("RABIT_ELASTIC", "0")).lower() in (
                "1", "true", "yes")
        self._adapt = str(
            params.get("rabit_adapt")
            or os.environ.get("RABIT_ADAPT", "0")).lower() in (
                "1", "true", "yes")
        super().init(params)  # rendezvous: rank known from here on
        if ckpt_dir:
            check(ckpt_keep >= 1, "rabit_ckpt_keep must be >= 1")
            self._ckpt_dir_raw = ckpt_dir
            self._ckpt_keep = ckpt_keep
            # Writer election: the first rabit_ckpt_writers ranks persist.
            # Default: rank 0 plus the ranks that ring-replicate its
            # local model — the same set whose RAM already holds the
            # hottest state, so adding disk IO there costs no extra
            # replication traffic.
            self._ckpt_writers = (int(writers_raw) if str(writers_raw)
                                  else 1 + self._num_local_replica)
            check(self._ckpt_writers >= 1,
                  "rabit_ckpt_writers must be >= 1")
            self._ckpt_store = ckpt_mod.CheckpointStore(
                ckpt_mod.expand_dir(ckpt_dir, self._rank),
                rank=self._rank, keep=ckpt_keep)
        self._num_trial = int(params.get("rabit_num_trial")
                              or os.environ.get("RABIT_NUM_TRIAL", 0))
        mock = (params.get("mock") or params.get("rabit_mock")
                or os.environ.get("RABIT_MOCK", ""))
        for spec in str(mock).split(";"):
            if not spec.strip():
                continue
            rank, version, seqno, ndeath = (int(x) for x in spec.split(","))
            if rank == self._rank:
                self._kill_points.add((version, seqno, ndeath))

    def shutdown(self) -> None:
        self._fence()  # async stream drains before straggler serving
        if self._world > 1 and self._links:
            try:
                # Serve stragglers (replay, checkpoint loads) until the
                # whole world reaches shutdown (reference:
                # src/allreduce_robust.cc Shutdown).
                self._recover_exec(K_SHUTDOWN, want_result=False)
            except Exception as e:  # noqa: BLE001 — best effort, peers may be gone
                self._log.debug("shutdown straggler serving abandoned: "
                                "%s: %s", type(e).__name__, e)
        super().shutdown()

    def _verify(self, seqno: int) -> None:
        """Mock kill-point: die with the restart exit code when this rank
        reaches (version, seqno) on its ndeath-th life (native analogue:
        MockEngine::Verify; reference: src/allreduce_mock.h:139-171)."""
        if (self._version, seqno, self._num_trial) in self._kill_points:
            self._log.warn("killed at kill-point seq=%d trial=%d",
                           seqno, self._num_trial)
            os._exit(254)  # the keepalive launcher's restart code

    # ------------------------------------------------------------------
    # consensus machinery
    # ------------------------------------------------------------------
    @staticmethod
    def _fingerprint(*parts) -> int:
        """Deterministic cross-process op fingerprint (never 0: zero
        marks 'no op pending' — checkpoint/load/shutdown states)."""
        raw = ":".join(str(p) for p in parts).encode()
        return (zlib.crc32(raw) & 0xFFFFFFFF) or 1

    def _merge_word(self, dst: np.ndarray, src: np.ndarray) -> None:
        """Pairwise consensus merge (native: RobustEngine::ReduceWord):
        OR the flags, keep min seqno + max version, derive divergence
        flags, and compare fingerprints only at an equal (seq, version)
        — fingerprints of different ops are incomparable."""
        df, ds, dv, dp = (int(x) for x in dst)
        sf, ss, sv, sp = (int(x) for x in src)
        flags = df | sf
        if ds != ss:
            flags |= K_DIFF_SEQ
        if dv != sv:
            flags |= K_DIFF_VERSION
        if ds == ss and dv == sv:
            if dp and sp and dp != sp:
                flags |= K_DIFF_OP
            fp = dp or sp
        else:
            fp = dp if ds < ss else sp  # min-seq side's op
        dst[0] = flags
        dst[1] = min(ds, ss)
        dst[2] = max(dv, sv)
        dst[3] = fp

    def _consensus(self, my_flag: int, fp: int = 0) -> tuple[int, int, int]:
        """One consensus allreduce with failure recovery built in
        (native: RobustEngine::Consensus).  Returns (flags, seq, version)
        agreed by the whole world."""
        while True:
            word = np.array([my_flag, self._seq, self._version, fp],
                            dtype=np.uint32)
            try:
                self._tree_chunked(
                    memoryview(word).cast("B"), 1, _WORD_BYTES,
                    lambda off, n, src: self._merge_word(
                        word, np.frombuffer(src, np.uint32, 4)))
                return int(word[0]), int(word[1]), int(word[2])
            except LinkError:
                self._recovering = True
                self._rendezvous_recover()

    def _agree_root(self, i_have: bool, key: int) -> int:
        """Agree on a serving root: max (key, then lowest rank); -1 when
        nobody has the item (native: RobustEngine::AgreeRoot)."""
        word = np.zeros(1, dtype=np.uint64)
        if i_have:
            word[0] = ((key + 1) << 20) | (0xFFFFF - self._rank)
        self._tree_chunked(
            memoryview(word).cast("B"), 1, 8,
            lambda off, n, src: np.maximum(
                word, np.frombuffer(src, np.uint64, 1), out=word))
        if word[0] == 0:
            return -1
        return 0xFFFFF - (int(word[0]) & 0xFFFFF)

    def _rendezvous_recover(self) -> None:
        """Cascade into a tracker recover round; retried because link
        setup itself can fail while more peers are still dying (the
        tracker docs this: survivors holding a topology that names a
        dead worker fail wiring and come back with cmd=recover).

        Bounded on two axes: at most ``rabit_recover_attempts`` failed
        rounds (each attempt already carries the full connect
        retry/backoff schedule), within the barrier deadline.
        Exhausting either budget raises :class:`RecoveryError` with the
        per-attempt failure history — fail fast and loud for the
        supervisor instead of spinning past rabit_timeout_sec
        semantics."""
        t0 = time.perf_counter()
        if self._obs_on:
            self._metrics.counter("recovery.link_errors").inc()
            self._emit_phase("link_error")
        deadline = time.monotonic() + (
            self.TRACKER_BARRIER_MIN_SEC if self._timeout is None
            else max(self._timeout, self.TRACKER_BARRIER_MIN_SEC))
        history: list[tuple[int, float, str]] = []
        old_world, old_epoch = self._world, self._epoch
        old_rank = self._rank
        while True:
            try:
                self._rendezvous(P.CMD_RECOVER)
                if self._obs_on:
                    dt = time.perf_counter() - t0
                    self._metrics.histogram(
                        "recovery.rendezvous.seconds").observe(dt)
                    self._emit_phase("rendezvous", dur=dt)
                if (self._world, self._epoch) != (old_world, old_epoch):
                    if (self._world, self._rank) == (old_world, old_rank):
                        # Same world, same rank, new epoch: a pure
                        # schedule-switch/demotion epoch (adaptive
                        # controller) resolved through this recover
                        # round — membership is unchanged, so the
                        # in-flight op and its caches stay valid.
                        self._sched_epoch(old_epoch)
                    else:
                        # The recover round completed as an elastic
                        # rescale (heartbeat-detected deaths shrank the
                        # target, or a pending grow resolved while we
                        # were re-registering): the in-flight op
                        # belongs to the dead world.
                        self._world_changed(old_world, old_epoch)
                return
            except OSError as e:
                attempt = len(history) + 1
                history.append((attempt, time.monotonic(),
                                f"{type(e).__name__}: {e}"))
                if self._obs_on:
                    self._metrics.counter(
                        "recovery.rendezvous.failures").inc()
                if (attempt >= self._recover_attempts
                        or time.monotonic() >= deadline):
                    if self._obs_on:
                        self._emit_phase("budget_exhausted",
                                         attempts=attempt)
                    narrative = "; ".join(
                        f"#{a} {err}" for a, _, err in history)
                    # Recovery escalation is a fault path: persist the
                    # flight record before failing loud (best effort,
                    # no-op without rabit_trace_dir).
                    self.flight_persist("recovery_budget_exhausted",
                                        attempts=attempt)
                    raise RecoveryError(
                        f"pyrobust: recover rendezvous failed {attempt} "
                        f"time(s) (budget {self._recover_attempts} "
                        f"attempts / barrier deadline) — tracker or "
                        f"peers unreachable: {narrative}", history)
                self._log.info("recover rendezvous failed (%s); "
                               "attempt %d/%d", e, attempt,
                               self._recover_attempts)
                # Recovery pacing keeps its own instruments: the net.*
                # counters are dial-level telemetry and the dials inside
                # each attempt already account for themselves there.
                delay_ms = self._backoff_delay_ms(attempt)
                if self._obs_on:
                    self._metrics.histogram(
                        "recovery.rendezvous.backoff.seconds").observe(
                        delay_ms / 1000.0)
                    self._emit_phase("backoff", attempt=attempt,
                                     delay_ms=round(delay_ms, 3))
                time.sleep(delay_ms / 1000.0)

    # ------------------------------------------------------------------
    # elastic membership
    # ------------------------------------------------------------------
    def _world_changed(self, old_world: int, old_epoch: int) -> None:
        """An elastic rescale landed: reset everything the old world
        owned and surface the typed error.

        Kept: the committed version and global model (every rank
        replicates them — that is exactly what the app resumes from)
        and this rank's durable store contents.  Void: the replay cache
        and seqno stream (results were computed BY the old world),
        local-model replicas (ring positions moved; local state is
        rank-affine and must be rebuilt from the re-sharded data) and
        any un-committed pending checkpoint.  The durable-store handle
        is re-created because the writer election and the ``{rank}``
        expansion follow the new rank."""
        self._cache.clear()
        self._seq = 0
        self._local_store.clear()
        self._local = None
        self._pending_lazy = None
        self._pending_global = b""
        self._has_pending_local = False
        self._recovering = False
        if self._ckpt_dir_raw:
            self._ckpt_store = ckpt_mod.CheckpointStore(
                ckpt_mod.expand_dir(self._ckpt_dir_raw, self._rank),
                rank=self._rank, keep=self._ckpt_keep)
        if self._obs_on:
            self._metrics.counter("elastic.rescales").inc()
            self._trace.emit("epoch", phase="rescale", rank=self._rank,
                             epoch=self._epoch, from_world=old_world,
                             world=self._world)
        self._log.info("membership epoch %d -> %d: world %d -> %d, now "
                       "rank %d; resuming from committed v%d",
                       old_epoch, self._epoch, old_world, self._world,
                       self._rank, self._version)
        raise WorldChangedError(old_world, self._world, self._epoch)

    def _sched_epoch(self, old_epoch: int) -> None:
        """A SAME-world, same-rank epoch landed: the tracker's adaptive
        controller pushed a schedule switch / straggler demotion (the
        rescale choreography at an unchanged membership), or an elastic
        member swap kept every survivor's rank.  Nothing rank-affine
        moved, so — unlike :meth:`_world_changed` — the replay cache,
        seqno stream and local replicas stay VALID and are kept: cached
        results are value-level (schedule-independent bytes), and a
        relaunched straggler mid-span still replays against them.  At a
        commit boundary (where controller pushes land) the cache is
        empty and seqno 0 anyway — the commit just cleared them.  No
        WorldChangedError: the app never notices, ops after this point
        simply ride the new directive every rank adopted in the same
        rendezvous round."""
        if self._obs_on:
            self._metrics.counter("sched.switch_epochs").inc()
            self._trace.emit("epoch", phase="sched_switch",
                             rank=self._rank, epoch=self._epoch,
                             world=self._world)
        self._log.info("schedule-switch epoch %d -> %d (world %d "
                       "unchanged): directive %r, demoted %s",
                       old_epoch, self._epoch, self._world,
                       sched_mod.encode_directive(self._sched_live),
                       sorted(self._demoted))

    def _poll_rescale_pending(self) -> bool:
        """Commit-boundary tracker poll: is a rescale epoch pending?
        Unreachable tracker == "no" — training never stalls on the
        poll; the consensus OR of every rank's answer (K_RESCALE)
        makes the final decision uniform even when polls race the
        tracker's admission bookkeeping."""
        polled = self._tracker_epoch_poll()
        if polled is None:
            return False
        _epoch, target_epoch, target_world = polled
        if target_epoch <= self._epoch:
            return False
        self._log.info("rescale pending at the tracker: epoch %d -> %d "
                       "(world %d -> %d); re-rendezvousing at this "
                       "commit boundary", self._epoch, target_epoch,
                       self._world, target_world)
        return True

    def _cooperative_rescale(self) -> None:
        """The agreed ack round carried K_RESCALE: every member leaves
        the commit boundary together into the tracker's rescale
        rendezvous.  If the target evaporated meanwhile (a parked
        joiner died), the round completes at the unchanged world and
        epoch — links are rewired, nothing is raised, training simply
        continues.  A SAME-world, same-rank epoch bump is a
        schedule-switch/demotion epoch from the adaptive controller:
        the new directive was adopted during the rendezvous and
        training continues without a WorldChangedError."""
        old_world, old_epoch = self._world, self._epoch
        old_rank = self._rank
        if self._obs_on:
            self._emit_phase("rescale_rendezvous", epoch=old_epoch)
        self._rendezvous(P.CMD_RESCALE)
        if (self._world, self._epoch) != (old_world, old_epoch):
            if (self._world, self._rank) == (old_world, old_rank):
                self._sched_epoch(old_epoch)
            else:
                self._world_changed(old_world, old_epoch)

    # ------------------------------------------------------------------
    # the recovery state machine
    # ------------------------------------------------------------------
    def _recover_exec(self, my_flag: int, want_result: bool,
                      fp: int = 0) -> Optional[bytes]:
        """Loop consensus rounds, serving recovery data, until the whole
        world is aligned at (my_flag, seq, version) — the native
        RecoverExec (reference: src/allreduce_robust.cc:832-902).

        Returns the cached result bytes when the caller's own collective
        was satisfied from a peer's replay cache (the caller must NOT
        execute it, nor call ``prepare_fun``); None once aligned.
        """
        loader = bool(my_flag & K_LOAD_CHECK)

        def _done(result: Optional[bytes]) -> Optional[bytes]:
            # World re-aligned after a recovery cascade: one "resume"
            # event closes the link_error -> rendezvous -> replay arc.
            if self._recovering:
                self._recovering = False
                if self._obs_on:
                    self._metrics.counter("recovery.resumes").inc()
                    self._emit_phase(
                        "resume",
                        kind="replayed" if result is not None else "fresh")
            return result

        while True:
            try:
                flags, seq, version = self._consensus(my_flag, fp)
                self._last_agreed = flags
                if flags & K_LOAD_CHECK:
                    if my_flag & K_CHECKPOINT:
                        # A relaunched peer is loading while we sit at
                        # the checkpoint barrier: commit FIRST so the
                        # loader is served the NEW version (see the
                        # native engine's comment for why serving the
                        # stale one resumes it into a dead iteration).
                        # Known corner (shared with the native engine,
                        # robust_engine.cc:68-80): this commit clears
                        # the replay cache, so a survivor starved of
                        # the final pre-checkpoint result by a real
                        # crash that split the tree mid-broadcast fails
                        # loudly on the version check below instead of
                        # being served — doc/fault_tolerance.md.
                        self._commit_checkpoint()
                        self._serve_checkpoint_load(loader)
                        return _done(None)  # barrier complete via early commit
                    served = self._serve_checkpoint_load(loader)
                    if loader and served:
                        return _done(None)
                    continue
                if flags & K_DIFF_VERSION:
                    if self._version < version:
                        if my_flag & K_CHECKPOINT:
                            # The epoch advanced while we were at the
                            # barrier: the commit already happened
                            # globally; commit ours now.
                            self._commit_checkpoint()
                            return _done(None)
                        error("pyrobust: version fell behind (%d < %d) "
                              "outside a checkpoint barrier — collective "
                              "call sequences diverged across ranks",
                              self._version, version)
                    continue  # someone else is catching up
                if flags & K_DIFF_SEQ:
                    got = self._serve_result(seq, want_result
                                             and my_flag == 0)
                    if got is not None:
                        return _done(got)
                    continue
                # Versions and seqnos are uniform across the world.
                agreed = flags
                if my_flag == 0:
                    check(not (agreed & K_DIFF_OP),
                          "pyrobust: ranks disagree on the op at "
                          "version=%d seq=%d (op type / reduce op / "
                          "payload size mismatch) — collective call "
                          "sequences diverged", self._version, self._seq)
                    if agreed == 0:
                        return _done(None)  # everyone ready: run the real op
                    continue  # checkpoint/shutdown stragglers draining
                if my_flag & K_CHECKPOINT:
                    if agreed == my_flag:
                        return _done(None)  # barrier complete
                    mine_wo_local = my_flag & ~K_LOCAL_CHK
                    if ((agreed & ~(K_LOCAL_CHK | K_DIFF_OP))
                            == mine_wo_local
                            and (agreed & K_LOCAL_CHK)
                            != (my_flag & K_LOCAL_CHK)):
                        error("pyrobust: local checkpoint model must be "
                              "passed on every rank or none (reference: "
                              "LocalModelCheck)")
                    continue
                if my_flag & K_CHECK_ACK:
                    # Commit phase done once nobody is still at the barrier.
                    if not (agreed & K_CHECKPOINT):
                        return _done(None)
                    continue
                if my_flag & K_SHUTDOWN:
                    if agreed == K_SHUTDOWN:
                        return _done(None)
                    continue
                continue
            except LinkError:
                self._recovering = True
                self._rendezvous_recover()

    def _serve_result(self, seq: int, i_want: bool) -> Optional[bytes]:
        """One serving round for the cached result of ``seq`` (native:
        ServeResult).  All ranks participate in the tree flood from the
        agreed holder; returns the bytes iff this rank is replaying
        exactly this seqno."""
        root = self._agree_root(seq in self._cache, 1)
        check(root >= 0,
              "pyrobust: result seq %d is cached nowhere — unrecoverable "
              "(raise rabit_global_replica)", seq)
        blob = self._cache[seq] if self._rank == root else None
        blob = self._bcast_impl(blob, root)
        wanted = i_want and self._seq == seq
        if self._obs_on:
            role = ("serve" if self._rank == root
                    else "recv" if wanted else "relay")
            self._metrics.counter("recovery.replay.count").inc()
            self._metrics.counter("recovery.replay.bytes").inc(len(blob))
            self._emit_phase("replay", kind=role, nbytes=len(blob),
                             seqno=seq)
        if wanted:
            return blob
        return None

    def _serve_checkpoint_load(self, i_am_loader: bool) -> bool:
        """Serve the newest checkpoint to (re)started loaders, then run
        local-model ring recovery (native: ServeCheckpointLoad).
        Returns True once a loader is satisfied."""
        root = self._agree_root(self._has_checkpoint, self._version)
        if root < 0:
            # No live rank holds a checkpoint: the durable-tier cold
            # path (or a genuinely fresh start at version 0).
            return self._cold_checkpoint_load(i_am_loader)
        if self._rank == root:
            self._materialize_global()
            blob = struct.pack("<I", self._version) + (self._global or b"")
        else:
            blob = None
        blob = self._bcast_impl(blob, root)
        if self._obs_on:
            self._emit_phase("checkpoint_serve", nbytes=len(blob),
                             kind="serve" if self._rank == root else
                             ("load" if i_am_loader else "relay"))
        if i_am_loader and self._rank != root:
            (bver,) = struct.unpack_from("<I", blob)
            # Version-skew guard BEFORE installing: a valid disk
            # checkpoint newer than the cluster-agreed version means
            # this rank's durable tier outran the live world (wrong
            # job, or the survivors lost committed state) — serving
            # the stale agreement would silently roll work backward.
            self._check_ckpt_skew(int(bver))
            self._version = int(bver)
            self._global = blob[4:]
            self._lazy_global = None  # received bytes supersede stale lazy
            self._has_checkpoint = True
            self._seq = 0
            self._cache.clear()
        # Local-model ring recovery: run whenever anyone anywhere holds
        # local state (all ranks must walk the ring passes together).
        if self._agree_root(bool(self._local_store), 1) >= 0:
            self._recover_local()
        return i_am_loader

    def _cold_checkpoint_load(self, i_am_loader: bool) -> bool:
        """Cold-restart path: nobody alive holds a checkpoint.

        Every rank runs the SAME agreement rounds (the store may be
        configured on only some ranks — e.g. writer-only disks — so the
        collective structure must not depend on rank-local config):

        1. unanimity check — a non-loader without a checkpoint is a
           live version-0 world mid-flight; loading an (older-job) disk
           version underneath it would fork versions, so disk is only
           consulted when EVERY rank is a loader;
        2. each rank reads its newest valid on-disk version, the world
           agrees on the max-version holder, and that rank re-serves
           the CRC-stamped blob verbatim over the tree flood.

        Falls through to the fresh version-0 start when no rank has a
        valid durable checkpoint."""
        someone_running = self._agree_root(not i_am_loader, 1) >= 0
        disk = None
        if not someone_running:
            disk = self._try_disk_read()
        droot = self._agree_root(disk is not None,
                                 disk.version if disk is not None else 0)
        if droot < 0:
            # Fresh start everywhere: loaders are satisfied with version 0.
            return True
        blob = disk.raw if self._rank == droot else None
        blob = self._bcast_impl(blob, droot)
        self._install_disk_checkpoint(bytes(blob))
        if self._obs_on:
            self._metrics.counter("checkpoint.cold_loads").inc()
            self._trace.emit("checkpoint", phase="cold_load",
                             rank=self._rank, version=self._version,
                             nbytes=len(blob),
                             kind="serve" if self._rank == droot
                             else "load")
        self._log.info("cold-restart: resumed version %d from the "
                       "durable tier (served by rank %d)",
                       self._version, droot)
        return i_am_loader

    def _try_disk_read(self) -> Optional[ckpt_mod.DiskCheckpoint]:
        if self._ckpt_store is None:
            return None
        try:
            return self._ckpt_store.load_latest()
        except OSError as e:
            self._log.warn("durable checkpoint read failed: %s", e)
            return None

    def _install_disk_checkpoint(self, raw: bytes) -> None:
        """Adopt a durable checkpoint blob as this rank's committed
        state (the CRC is re-verified — the bytes crossed the wire)."""
        try:
            dc = ckpt_mod.unpack_blob(raw)
        except ValueError as e:
            error("pyrobust: served durable checkpoint is invalid: %s", e)
        self._version = dc.version
        self._global = dc.global_blob
        self._lazy_global = None
        self._has_checkpoint = True
        self._seq = 0
        self._cache.clear()
        if dc.world == self._world:
            for origin, blob in dc.locals.items():
                dist = (self._rank - origin) % self._world
                if origin == self._rank or dist <= self._num_local_replica:
                    self._local_store[origin] = (dc.version, blob)
            if self._rank in dc.locals:
                self._local = dc.locals[self._rank]
        elif dc.locals:
            self._log.warn("durable checkpoint was written by a world of "
                           "%d (now %d); local models discarded, global "
                           "state kept", dc.world, self._world)

    def _check_ckpt_skew(self, agreed_version: int) -> None:
        """Note this cannot misfire on a writer that persisted and died
        mid-barrier: a loader arriving at the checkpoint barrier makes
        every survivor commit FIRST (the commit-early rule in
        _recover_exec), so the version the world serves always catches
        up to anything a writer managed to persist; genuinely newer
        disk therefore means foreign or lost state — fail loudly."""
        if self._ckpt_store is None:
            return
        newest = self._ckpt_store.newest_version(
            min_version=agreed_version)
        if newest is not None and newest > agreed_version:
            if self._obs_on:
                self._trace.emit("checkpoint", phase="skew",
                                 rank=self._rank, version=agreed_version,
                                 disk_version=newest)
            raise ckpt_mod.CheckpointSkewError(newest, agreed_version)

    # ------------------------------------------------------------------
    # collectives with replay
    # ------------------------------------------------------------------
    def _striped(self, seq: int) -> bool:
        rnd = max(self._world // self._num_global_replica, 1)
        return seq % rnd == self._rank % rnd

    def _prune_stale(self) -> None:
        """Striped replication bounds cache memory (reference:
        src/allreduce_robust.cc:86-89).  Runs after the consensus round,
        never at push time — a peer that died mid-op recovers the newest
        result from *any* completer."""
        for seq in [s for s in self._cache if not self._striped(s)]:
            del self._cache[seq]

    def _push_result(self, blob: bytes) -> None:
        self._cache[self._seq] = blob
        self._seq += 1

    def _run_collective(self, attempt: Callable[[], bytes], nbytes: int,
                        fp: int) -> bytes:
        """Run ``attempt`` (the real op on a working copy — the user
        buffer stays pristine for retries) with recovery: on LinkError,
        re-rendezvous and either replay the result a completer cached or
        retry the op once the world re-aligns (native: RunCollective)."""
        while True:
            try:
                return attempt()
            except LinkError:
                self._recovering = True
                self._rendezvous_recover()
                recovered = self._recover_exec(0, want_result=True, fp=fp)
                if recovered is not None:
                    check(len(recovered) == nbytes,
                          "pyrobust: recovered result size %d != expected "
                          "%d — collective call sequences diverged",
                          len(recovered), nbytes)
                    return recovered

    def _allreduce_blocking(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        codec: bool = True,
    ) -> np.ndarray:
        # The robust op body; the public blocking entry point (inherited
        # from PySocketEngine) fences the async stream first, and the
        # async progress thread runs this directly — either way the
        # seqno stream sees one ordered op sequence.  The wire codec
        # composes below the cache: results are cached/replayed as
        # DECODED full-width bytes, the codec's error-feedback commit
        # is transactional (a LinkError retries from the pristine
        # buffer with identical wire bytes), and the fingerprint covers
        # the logical op — so replay is bit-identical with any codec.
        self._verify(self._seq)
        self._last_replayed = False
        if self._world == 1:
            if prepare_fun is not None:
                prepare_fun()
            self._seq += 1
            return buf
        t0 = time.perf_counter() if self._obs_on else 0.0
        flat = buf.reshape(-1)
        nbytes = flat.nbytes
        fp = self._fingerprint("allreduce", int(op), buf.dtype.str, nbytes)
        recovered = self._recover_exec(0, want_result=True, fp=fp)
        if recovered is not None:
            self._last_replayed = True
            check(len(recovered) == nbytes,
                  "pyrobust: recovered allreduce size %d != %d",
                  len(recovered), nbytes)
            flat[:] = np.frombuffer(recovered, dtype=flat.dtype)
            self._prune_stale()
            if self._obs_on:
                self._op_done("allreduce", nbytes, t0, replayed=True)
            self._push_result(recovered)
            return buf
        self._prune_stale()
        if prepare_fun is not None:
            prepare_fun()

        def attempt() -> bytes:
            work = flat.copy()
            self._allreduce_impl(work, op, codec)
            return work.tobytes()

        result = self._run_collective(attempt, nbytes, fp)
        flat[:] = np.frombuffer(result, dtype=flat.dtype)
        if self._obs_on:
            self._op_done("allreduce", nbytes, t0)
        self._push_result(result)
        return buf

    def _allreduce_custom_blocking(self, buf: np.ndarray, reducer,
                                   prepare_fun=None) -> np.ndarray:
        self._verify(self._seq)
        self._last_replayed = False
        if self._world == 1:
            if prepare_fun is not None:
                prepare_fun()
            self._seq += 1
            return buf
        t0 = time.perf_counter() if self._obs_on else 0.0
        nbytes = buf.nbytes
        fp = self._fingerprint("custom", buf.dtype.str, buf.shape)
        recovered = self._recover_exec(0, want_result=True, fp=fp)
        if recovered is not None:
            self._last_replayed = True
            check(len(recovered) == nbytes,
                  "pyrobust: recovered custom allreduce size %d != %d",
                  len(recovered), nbytes)
            buf.reshape(-1)[:] = np.frombuffer(recovered, dtype=buf.dtype)
            self._prune_stale()
            if self._obs_on:
                self._op_done("allreduce_custom", nbytes, t0, replayed=True)
            self._push_result(recovered)
            return buf
        self._prune_stale()
        if prepare_fun is not None:
            prepare_fun()

        def attempt() -> bytes:
            work = buf.copy()
            self._allreduce_custom_impl(work, reducer)
            return work.tobytes()

        result = self._run_collective(attempt, nbytes, fp)
        buf.reshape(-1)[:] = np.frombuffer(result, dtype=buf.dtype)
        if self._obs_on:
            self._op_done("allreduce_custom", nbytes, t0)
        self._push_result(result)
        return buf

    def _broadcast_blocking(self, data: Optional[bytes], root: int) -> bytes:
        self._verify(self._seq)
        self._last_replayed = False
        if self._world == 1:
            check(data is not None, "broadcast: root rank must supply data")
            self._seq += 1
            return data
        # Payload size is root-only knowledge, so the fingerprint covers
        # the op type and root; the replay path checks the size at the
        # root, which does know it.
        t0 = time.perf_counter() if self._obs_on else 0.0
        fp = self._fingerprint("broadcast", root)
        recovered = self._recover_exec(0, want_result=True, fp=fp)
        if recovered is not None:
            self._last_replayed = True
            # Only the root knows the payload size; a cached result that
            # disagrees with what this (relaunched) root would have sent
            # means the call sequences diverged.
            check(data is None or len(recovered) == len(data),
                  "pyrobust: recovered broadcast size %d != root payload "
                  "%d — collective call sequences diverged",
                  len(recovered), len(data or b""))
            self._prune_stale()
            if self._obs_on:
                self._op_done("broadcast", len(recovered), t0, replayed=True)
            self._push_result(recovered)
            return recovered
        self._prune_stale()
        while True:
            try:
                out = self._bcast_impl(data, root)
                break
            except LinkError:
                self._recovering = True
                self._rendezvous_recover()
                recovered = self._recover_exec(0, want_result=True, fp=fp)
                if recovered is not None:
                    out = recovered
                    break
        out = bytes(out)
        if self._obs_on:
            self._op_done("broadcast", len(out), t0)
        self._push_result(out)
        return out

    def _allgather_blocking(self, buf: np.ndarray) -> np.ndarray:
        self._verify(self._seq)
        self._last_replayed = False
        if self._world == 1:
            self._seq += 1
            return buf[None]
        t0 = time.perf_counter() if self._obs_on else 0.0
        total = buf.nbytes * self._world
        shape = (self._world,) + buf.shape
        fp = self._fingerprint("allgather", buf.dtype.str, buf.nbytes)
        recovered = self._recover_exec(0, want_result=True, fp=fp)
        if recovered is not None:
            self._last_replayed = True
            check(len(recovered) == total,
                  "pyrobust: recovered allgather size %d != %d",
                  len(recovered), total)
            self._prune_stale()
            if self._obs_on:
                self._op_done("allgather", total, t0, replayed=True)
            self._push_result(recovered)
            return np.frombuffer(recovered,
                                 dtype=buf.dtype).reshape(shape).copy()
        self._prune_stale()

        def attempt() -> bytes:
            return self._allgather_impl(buf).tobytes()

        result = self._run_collective(attempt, total, fp)
        if self._obs_on:
            self._op_done("allgather", total, t0)
        self._push_result(result)
        return np.frombuffer(result, dtype=buf.dtype).reshape(shape).copy()

    def _fused_allreduce_exec(self, items: list, op,
                              codec_ok: bool = True) -> None:
        """Bucket-fused allreduce under the robust protocol: the whole
        bucket is ONE collective — one consensus round, one seqno, one
        cached result — so replay after a failure serves the fused
        payload exactly as it serves any other op.  Bucket boundaries
        are deterministic in program order (flush on size/op/dtype/wait
        triggers only), so a relaunched rank re-issuing the same async
        stream reproduces the same seqno map as the survivors."""
        self._verify(self._seq)
        self._last_replayed = False
        t0 = time.perf_counter() if self._obs_on else 0.0
        flats = [it[0] for it in items]
        dtype = flats[0].dtype
        sizes = tuple(len(f) for f in flats)
        nbytes = int(sum(sizes)) * dtype.itemsize
        fp = self._fingerprint("fused_allreduce", int(op), dtype.str, sizes)
        recovered = self._recover_exec(0, want_result=True, fp=fp)
        if recovered is not None:
            self._last_replayed = True
            check(len(recovered) == nbytes,
                  "pyrobust: recovered fused allreduce size %d != %d",
                  len(recovered), nbytes)
            # Replay: members' prepare_funs are skipped, like any
            # cache-served collective.
            self._scatter_fused(flats, np.frombuffer(recovered, dtype=dtype))
            self._prune_stale()
            if self._obs_on:
                self._record_fusion(len(items), nbytes, t0, replayed=True)
            self._push_result(recovered)
            for _flat, buf, _prep, h in items:
                self._resolve_handle(h, buf)
            return
        self._prune_stale()
        for _flat, _buf, prep, _h in items:
            if prep is not None:
                prep()
        pristine = np.concatenate(flats)

        def attempt() -> bytes:
            # Member arrays must be pristine on every retry (a LinkError
            # can strike mid-reduction, leaving them partially merged).
            self._scatter_fused(flats, pristine)
            self._fused_wire(flats, op, codec_ok)
            return np.concatenate(flats).tobytes()

        result = self._run_collective(attempt, nbytes, fp)
        self._scatter_fused(flats, np.frombuffer(result, dtype=dtype))
        if self._obs_on:
            self._record_fusion(len(items), nbytes, t0)
        self._push_result(result)
        for _flat, buf, _prep, h in items:
            self._resolve_handle(h, buf)

    @property
    def last_op_replayed(self) -> bool:
        """True iff the LAST collective was served from the replay cache
        (the op completed before this relaunched rank joined).  Mid-op
        recovery — this rank participated, a peer died, the result was
        recovered — counts as fresh, exactly like the native engine."""
        return self._last_replayed

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------
    def _materialize_global(self) -> None:
        if self._lazy_global is not None:
            self._global = self._lazy_global()
            self._lazy_global = None

    def _commit_checkpoint(self) -> None:
        if self._pending_lazy is not None:
            self._lazy_global = self._pending_lazy
            self._pending_lazy = None
            self._global = b""
        else:
            self._global = self._pending_global
            self._lazy_global = None
        self._has_checkpoint = True
        self._version += 1
        if self._has_pending_local:
            self._local_store[self._rank] = (self._version,
                                             self._pending_local)
            self._local = self._pending_local  # world-of-1 load path
        self._cache.clear()
        self._seq = 0
        if self._obs_on:
            self._metrics.counter("checkpoint.commits").inc()
            # Live-plane gauge: the streamed frames carry it, so a
            # /metrics scrape shows each rank's committed progress
            # mid-run (the cmd=epoch poll only reports in elastic mode).
            self._metrics.gauge("ckpt.committed_version").set(
                self._version)
            self._trace.emit("checkpoint", phase="commit", rank=self._rank,
                             version=self._version)
        if self._is_ckpt_writer():
            self._persist_checkpoint()

    def _is_ckpt_writer(self) -> bool:
        return (self._ckpt_store is not None
                and self._rank < min(self._ckpt_writers, self._world))

    def _persist_checkpoint(self) -> None:
        """Durably persist the just-committed version (writer ranks
        only).  Persistence is synchronous inside the commit, so a
        persisted version is always one the whole world agreed (at the
        checkpoint barrier) to commit; failures degrade durability
        (logged + counted), they never kill the job — the RAM replicas
        still cover it."""
        t0 = time.perf_counter()
        try:
            self._materialize_global()  # lazy blobs must hit the disk too
            locals_ = {origin: blob
                       for origin, (version, blob)
                       in self._local_store.items()
                       if version == self._version}
            self._ckpt_store.persist(self._version, self._world,
                                     self._global or b"", locals_)
        except OSError as e:
            self._log.warn("durable checkpoint persist failed (v%d): %s",
                           self._version, e)
            if self._obs_on:
                self._metrics.counter("checkpoint.persist.failures").inc()
            return
        if self._obs_on:
            dt = time.perf_counter() - t0
            nbytes = len(self._global or b"") + sum(
                len(b) for b in locals_.values())
            self._metrics.counter("checkpoint.persist.count").inc()
            self._metrics.counter("checkpoint.persist.bytes").inc(nbytes)
            self._metrics.histogram(
                "checkpoint.persist.seconds").observe(dt)
            self._trace.emit("checkpoint", phase="persist",
                             rank=self._rank, version=self._version,
                             nbytes=nbytes, dur=dt)

    def checkpoint(self, global_model, local_model=None,
                   lazy_global=None) -> None:
        self._fence()  # in-flight async ops belong to this version span
        self._verify(SEQ_CHECKPOINT)
        if global_model is None and lazy_global is not None:
            self._pending_global = b""
            self._pending_lazy = lazy_global
        else:
            self._pending_global = global_model or b""
            self._pending_lazy = None
        self._has_pending_local = local_model is not None
        self._pending_local = local_model or b""
        if self._world == 1:
            self._commit_checkpoint()
            if (self._elastic or self._adapt) \
                    and self._poll_rescale_pending():
                # A lone rank can still grow: joiners parked at the
                # tracker make the next commit a rescale boundary too.
                self._cooperative_rescale()
            return
        flag = K_CHECKPOINT | (K_LOCAL_CHK if self._has_pending_local else 0)
        version_before = self._version
        self._recover_exec(flag, want_result=False)
        if self._version == version_before:  # not committed via catch-up
            if self._has_pending_local:
                # Every rank exits the barrier on the same consensus
                # round, so the ring replication passes align globally.
                self._local_store[self._rank] = (self._version + 1,
                                                 self._pending_local)
                try:
                    self._replicate_local()
                except LinkError:
                    # Degraded: this checkpoint's local blobs are
                    # under-replicated until the next one; global safety
                    # is unaffected.
                    self._rendezvous_recover()
            self._commit_checkpoint()
        ack = K_CHECK_ACK
        if (self._elastic or self._adapt) \
                and self._poll_rescale_pending():
            ack |= K_RESCALE
        self._recover_exec(ack, want_result=False)
        if (self._elastic or self._adapt) \
                and (self._last_agreed & K_RESCALE):
            # Some rank's poll saw a pending epoch; the OR-merged ack
            # made it everyone's decision.  The commit above is already
            # durable on every survivor — this raises WorldChangedError
            # once the new topology lands (a pure schedule-switch epoch
            # at the unchanged world raises nothing and just adopts the
            # new directive).
            self._cooperative_rescale()

    def load_checkpoint(self):
        self._fence()
        self._verify(SEQ_LOAD_CHECK)
        if self._world == 1:
            if not self._has_checkpoint:
                disk = self._try_disk_read()
                if disk is None:
                    return (0, None, None)
                self._install_disk_checkpoint(disk.raw)
                self._log.info("cold-restart: resumed version %d from "
                               "the durable tier", self._version)
            self._materialize_global()
            return (self._version, self._global, self._local)
        self._recover_exec(K_LOAD_CHECK, want_result=False)
        if not self._has_checkpoint:
            return (0, None, None)
        self._materialize_global()
        local = None
        entry = self._local_store.get(self._rank)
        if entry is not None and entry[0] == self._version:
            local = entry[1]
        self._seq = 0
        return (self._version, self._global or None, local)

    # ------------------------------------------------------------------
    # local-model ring replication
    # ------------------------------------------------------------------
    def _ring_pass_blobs(self, backward: bool) -> None:
        """Exchange the whole local store with ring neighbours and merge
        keeping the highest version per origin (native: RingPassBlobs).
        Forward pass sends toward ring_next; backward toward ring_prev."""
        out = bytearray(struct.pack("<I", len(self._local_store)))
        for origin, (version, blob) in sorted(self._local_store.items()):
            out += struct.pack("<IIQ", origin, version, len(blob))
            out += blob
        send_rank = self._ring_prev if backward else self._ring_next
        recv_rank = self._ring_next if backward else self._ring_prev
        in_size = memoryview(bytearray(8))
        self._exchange(send_rank, memoryview(struct.pack("<Q", len(out))),
                       recv_rank, in_size)
        (n_in,) = struct.unpack("<Q", bytes(in_size))
        incoming = memoryview(bytearray(n_in))
        self._exchange(send_rank, memoryview(out), recv_rank, incoming)
        raw = bytes(incoming)
        (count,) = struct.unpack_from("<I", raw, 0)
        pos = 4
        for _ in range(count):
            origin, version, length = struct.unpack_from("<IIQ", raw, pos)
            pos += 16
            blob = raw[pos:pos + length]
            pos += length
            have = self._local_store.get(int(origin))
            if have is None or have[0] < int(version):
                self._local_store[int(origin)] = (int(version), blob)

    def _replicate_local(self) -> None:
        """Push blobs forward so ranks r+1..r+K hold origin r's state,
        then prune to the origins this rank is responsible for."""
        for _ in range(self._num_local_replica):
            self._ring_pass_blobs(backward=False)
        for origin in list(self._local_store):
            dist = (self._rank - origin) % self._world
            if dist > self._num_local_replica:
                del self._local_store[origin]

    def _recover_local(self) -> None:
        """Backward floods bring each origin's blob back to the origin
        (any survivor within K successors holds it), then forward floods
        restore the replication invariant."""
        for _ in range(self._num_local_replica):
            self._ring_pass_blobs(backward=True)
        self._replicate_local()
