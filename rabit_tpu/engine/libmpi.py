"""mpi4py-compatible ctypes binding over the system libmpi.

The TPU image ships OpenMPI's runtime library (libmpi.so.40 + MCA
plugins) but neither mpi4py nor the -dev headers.  This module binds the
small MPI surface the framework's MPI engine needs straight to the real
library, so ``rabit_engine=mpi`` executes genuine MPI_Allreduce /
MPI_Bcast calls when launched under mpirun (the rebuilt front-end in
``rabit_tpu/native/mpi`` or any system one).  The API mirrors mpi4py's
shape — ``MPI.COMM_WORLD``, ``Get_rank``, ``Allreduce(IN_PLACE, buf,
op=MPI.SUM)`` — so the engine treats the two interchangeably.

TPU-native equivalent of the vendor mpi.h the reference's MPI engine
compiles against (reference: src/engine_mpi.cc:20-205).  The predefined
handles are addresses of the documented exported ``ompi_mpi_*`` storage
objects, the same public OpenMPI ABI the C shim header
(``native/mpi/ompi_abi.h``) declares.
"""
from __future__ import annotations

import atexit
import ctypes
import ctypes.util
import pickle
from typing import Any, Optional

import numpy as np

_LIB_CANDIDATES = (
    "libmpi.so.40",          # OpenMPI 4.x (this image)
    "libmpi.so.20",          # OpenMPI 2.x
    "libmpi.so",
)


def _load() -> Optional[ctypes.CDLL]:
    for name in _LIB_CANDIDATES:
        try:
            # RTLD_GLOBAL: OpenMPI dlopens MCA plugins that resolve
            # symbols against the already-loaded libmpi
            return ctypes.CDLL(name, mode=ctypes.RTLD_GLOBAL)
        except OSError:
            continue
    return None


_lib = _load()


def available() -> bool:
    return _lib is not None


def _handle(sym: str) -> ctypes.c_void_p:
    """Address of an exported predefined-handle storage object."""
    return ctypes.c_void_p(
        ctypes.addressof((ctypes.c_char * 1).in_dll(_lib, sym)))


class _Op:
    def __init__(self, sym: str) -> None:
        self.h = _handle(sym)


class _Datatype:
    def __init__(self, sym: str) -> None:
        self.h = _handle(sym)


IN_PLACE = ctypes.c_void_p(1)      # OpenMPI ABI: MPI_IN_PLACE == (void*)1

if _lib is not None:
    try:
        SUM = _Op("ompi_mpi_op_sum")
        MAX = _Op("ompi_mpi_op_max")
        MIN = _Op("ompi_mpi_op_min")
        PROD = _Op("ompi_mpi_op_prod")
        BOR = _Op("ompi_mpi_op_bor")
        BAND = _Op("ompi_mpi_op_band")
        BXOR = _Op("ompi_mpi_op_bxor")

        _DTYPES = {
            np.dtype(np.float32): _Datatype("ompi_mpi_float"),
            np.dtype(np.float64): _Datatype("ompi_mpi_double"),
            np.dtype(np.int8): _Datatype("ompi_mpi_signed_char"),
            np.dtype(np.uint8): _Datatype("ompi_mpi_unsigned_char"),
            np.dtype(np.int32): _Datatype("ompi_mpi_int"),
            np.dtype(np.uint32): _Datatype("ompi_mpi_unsigned"),
            np.dtype(np.int64): _Datatype("ompi_mpi_long"),
            np.dtype(np.uint64): _Datatype("ompi_mpi_unsigned_long"),
        }
        _BYTE = _Datatype("ompi_mpi_unsigned_char")
        _COMM_WORLD_H = _handle("ompi_mpi_comm_world")
    except ValueError:
        # the resolvable libmpi is not OpenMPI (e.g. MPICH): the
        # ompi_mpi_* predefined-handle symbols this binding depends on
        # are absent — report the binding unavailable instead of
        # exploding at import time
        _lib = None

_initialized = False
_finalized = False


def _errcheck(rc: int, what: str) -> None:
    if rc != 0:
        raise RuntimeError(f"{what} failed with MPI error {rc}")


def _ensure_init() -> None:
    global _initialized
    if _initialized:
        return
    flag = ctypes.c_int(0)
    _lib.MPI_Initialized(ctypes.byref(flag))
    if not flag.value:
        _errcheck(_lib.MPI_Init(None, None), "MPI_Init")
    _initialized = True
    atexit.register(_finalize)


def _finalize() -> None:
    global _finalized
    if _finalized or _lib is None:
        return
    flag = ctypes.c_int(0)
    _lib.MPI_Finalized(ctypes.byref(flag))
    if not flag.value:
        _lib.MPI_Finalize()
    _finalized = True


def _buf_ptr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


class Comm:
    """The COMM_WORLD slice of mpi4py's Comm API."""

    def __init__(self) -> None:
        _ensure_init()
        self.h = _COMM_WORLD_H

    def Get_rank(self) -> int:
        out = ctypes.c_int(-1)
        _errcheck(_lib.MPI_Comm_rank(self.h, ctypes.byref(out)),
                  "MPI_Comm_rank")
        return out.value

    def Get_size(self) -> int:
        out = ctypes.c_int(-1)
        _errcheck(_lib.MPI_Comm_size(self.h, ctypes.byref(out)),
                  "MPI_Comm_size")
        return out.value

    def Barrier(self) -> None:
        _errcheck(_lib.MPI_Barrier(self.h), "MPI_Barrier")

    def Allreduce(self, sendbuf: Any, recvbuf: np.ndarray, op: _Op) -> None:
        a = np.ascontiguousarray(recvbuf)
        check_inplace = (sendbuf is IN_PLACE
                         or getattr(sendbuf, "value", None) == 1)
        if not check_inplace:
            raise ValueError("libmpi shim supports IN_PLACE Allreduce only")
        if a is not recvbuf:
            raise ValueError("Allreduce buffer must be contiguous")
        dt = _DTYPES.get(a.dtype)
        if dt is None:
            raise ValueError(f"unsupported dtype {a.dtype}")
        _errcheck(_lib.MPI_Allreduce(IN_PLACE, _buf_ptr(a), a.size, dt.h,
                                     op.h, self.h), "MPI_Allreduce")

    def Allgather(self, sendbuf: np.ndarray, recvbuf: np.ndarray) -> None:
        s = np.ascontiguousarray(sendbuf)
        dt = _DTYPES.get(s.dtype)
        if dt is None or recvbuf.dtype != s.dtype \
                or not recvbuf.flags.c_contiguous:
            raise ValueError("Allgather needs matching contiguous buffers")
        _errcheck(_lib.MPI_Allgather(_buf_ptr(s), s.size, dt.h,
                                     _buf_ptr(recvbuf), s.size, dt.h,
                                     self.h), "MPI_Allgather")

    def Bcast(self, buf: np.ndarray, root: int = 0) -> None:
        a = buf
        if not a.flags.c_contiguous:
            raise ValueError("Bcast buffer must be contiguous")
        _errcheck(_lib.MPI_Bcast(_buf_ptr(a), a.nbytes, _BYTE.h, root,
                                 self.h), "MPI_Bcast")

    def bcast(self, obj: Any, root: int = 0) -> Any:
        """Pickle-object broadcast: length then payload, mirroring
        mpi4py's lowercase API (and the reference Python binding's
        2-phase scheme, /root/reference/wrapper/rabit.py:117-168)."""
        rank = self.Get_rank()
        if rank == root:
            payload = np.frombuffer(
                pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                np.uint8).copy()
            n = np.array([payload.size], np.int64)
        else:
            payload = None
            n = np.zeros(1, np.int64)
        self.Bcast(n, root)
        if rank != root:
            payload = np.empty(int(n[0]), np.uint8)
        self.Bcast(payload, root)
        return obj if rank == root else pickle.loads(payload.tobytes())


COMM_WORLD: Optional[Comm] = None


def comm_world() -> Comm:
    """Lazy COMM_WORLD (MPI_Init on first use, like mpi4py's import)."""
    global COMM_WORLD
    if COMM_WORLD is None:
        if _lib is None:
            raise RuntimeError("no libmpi on this system")
        COMM_WORLD = Comm()
    return COMM_WORLD
