"""Abstract engine interface.

TPU-native equivalent of the reference's IEngine
(reference: include/rabit/engine.h:22-157): the contract every collective
backend implements — in-place allreduce, any-root broadcast, the checkpoint
trio, and identity/topology queries.

Differences from the reference, by design:

* Buffers are numpy arrays (host engines) or ``jax.Array`` (XLA engine)
  rather than ``void*`` — the byte-level view lives in the native layer.
* ``allgather`` is added: it is a first-class XLA collective and several
  rabit-learn algorithms express better with it.
* Checkpoint payloads are ``bytes`` at this layer; object (de)serialization
  happens above (see rabit_tpu.utils.serial).
"""
from __future__ import annotations

import threading
from abc import ABC, abstractmethod
from typing import Callable, Optional

import numpy as np

from rabit_tpu.ops import ReduceOp


class AsyncOrderError(RuntimeError):
    """An async handle was waited out of issue order.

    Async collectives resolve strictly in issue order (the wire stream
    is one ordered sequence on every engine); waiting handle N before
    every handle issued earlier has been waited would deadlock or
    reorder the stream, so it fails loudly instead.
    """


class CollectiveHandle:
    """Waitable result of an async collective (``allreduce_async`` /
    ``allgather_async``).

    ``wait()`` blocks until the op completes and returns its result —
    the same object the blocking call would return (the caller's array
    for in-place allreduce, a new array for allgather).  A failure
    inside the engine's progress machinery (e.g. a peer death on a
    non-fault-tolerant engine) re-raises at ``wait()``.  ``wait()`` is
    idempotent; handles from an async-capable engine must be waited in
    issue order (see :class:`AsyncOrderError`).

    Engines without a real async path return handles that are born
    resolved (the op ran synchronously at issue time), so callers can
    use the handle API unconditionally.
    """

    def __init__(self, on_wait: Optional[Callable[["CollectiveHandle"],
                                                  None]] = None) -> None:
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._on_wait = on_wait
        self._waited = False

    @classmethod
    def resolved(cls, result) -> "CollectiveHandle":
        """A handle born complete (synchronous engines)."""
        h = cls()
        h._resolve(result)
        return h

    def _resolve(self, result) -> None:
        self._result = result
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def done(self) -> bool:
        """True once the op has completed (successfully or not)."""
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None):
        """Block until the op completes; return its result or re-raise
        the failure that stopped it."""
        if self._on_wait is not None and not self._waited:
            # Engine hook: issue-order enforcement, pending-bucket flush
            # and overlap accounting happen before we block.
            self._on_wait(self)
        self._waited = True
        if not self._event.wait(timeout):
            raise TimeoutError("CollectiveHandle.wait timed out")
        if self._error is not None:
            raise self._error
        return self._result


class Engine(ABC):
    """One collective-communication backend."""

    # ---- lifecycle ------------------------------------------------------
    @abstractmethod
    def init(self, params: dict) -> None:
        """Connect/rendezvous.  ``params`` are untyped name→value settings
        (reference: SetParam cascade, src/allreduce_base.cc:111-133)."""

    @abstractmethod
    def shutdown(self) -> None:
        """Leave the job cleanly (reference: IEngine::Shutdown)."""

    # ---- identity / topology -------------------------------------------
    @property
    @abstractmethod
    def rank(self) -> int: ...

    @property
    @abstractmethod
    def world_size(self) -> int: ...

    @property
    def host(self) -> str:
        import socket

        return socket.gethostname()

    def is_distributed(self) -> bool:
        return self.world_size > 1

    @property
    def was_relaunched(self) -> bool:
        """True iff this process is a mid-job relaunch of a worker that
        already completed a rendezvous round (tracker-detected — works
        even when the restarting platform passes a clean environment).
        Engines with a tracker override this."""
        return False

    @property
    def last_op_replayed(self) -> bool:
        """True iff the LAST collective's result was served from the
        fault-tolerance replay cache (the op completed before this
        relaunched rank joined).  Always False for engines without
        replay; the robust native engine overrides this.  The XLA
        engine uses it to avoid acting on a replayed device-plane
        re-formation."""
        return False

    # ---- collectives ----------------------------------------------------
    @abstractmethod
    def allreduce(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        codec: bool = True,
    ) -> np.ndarray:
        """In-place allreduce of ``buf`` across all ranks.

        ``prepare_fun`` is the lazy-preparation hook: it must fill ``buf``
        and is *skipped* when a cached result is replayed during recovery
        (reference: include/rabit/engine.h:58-76, src/allreduce_robust.cc:90).
        ``codec=False`` opts this op out of an armed lossy wire codec
        (``rabit_wire_codec`` — doc/performance.md "Quantized wire
        codecs"): precision-critical ops keep exact full-width bytes.
        Engines without a codec-capable wire accept and ignore it.
        """

    @abstractmethod
    def broadcast(self, data: Optional[bytes], root: int) -> bytes:
        """Any-root broadcast of a byte payload; returns the payload on all
        ranks (reference: IEngine::Broadcast, src/allreduce_base.cc:500-588)."""

    def allgather(self, buf: np.ndarray) -> np.ndarray:
        """Gather each rank's ``buf`` into shape (world, *buf.shape).

        Default implementation composes broadcasts; backends override with a
        real collective.  (Extension over the reference.)
        """
        parts = []
        for r in range(self.world_size):
            payload = buf.tobytes() if r == self.rank else None
            raw = self.broadcast(payload, root=r)
            parts.append(np.frombuffer(raw, dtype=buf.dtype).reshape(buf.shape))
        return np.stack(parts)

    # ---- async collectives ----------------------------------------------
    def allreduce_async(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        fuse: bool = True,
        codec: bool = True,
    ) -> CollectiveHandle:
        """Issue an in-place allreduce and return a waitable
        :class:`CollectiveHandle` instead of blocking.

        The default runs the op synchronously and returns a resolved
        handle, so every engine supports the handle API; engines with a
        background progress thread (pysocket/pyrobust) override this to
        overlap socket I/O with the caller's compute and to coalesce
        streams of small same-op/same-dtype payloads into fused wire
        ops (``rabit_bucket_bytes``; pass ``fuse=False`` for a lone
        latency-sensitive op so it dispatches eagerly instead of
        waiting in the bucket).  ``buf`` must not be touched between
        issue and ``wait()``.
        """
        return CollectiveHandle.resolved(
            self.allreduce(buf, op, prepare_fun, codec))

    def allgather_async(self, buf: np.ndarray) -> CollectiveHandle:
        """Issue an allgather; ``wait()`` returns the (world, *shape)
        result.  Default is synchronous (see :meth:`allreduce_async`)."""
        return CollectiveHandle.resolved(self.allgather(buf))

    def allreduce_custom(
        self,
        buf: np.ndarray,
        reducer: Callable[[np.ndarray, np.ndarray], None],
        prepare_fun: Optional[Callable[[], None]] = None,
    ) -> np.ndarray:
        """In-place allreduce with a user-defined reducer (an extension;
        the reference exposes this only in C++ — ReduceHandle,
        include/rabit/engine.h:215-253).

        ``reducer(dst, src)`` must fold ``src`` into ``dst`` in place and
        be **associative and commutative** — merge order is unspecified
        and engine-dependent (this default folds in rank order, but the
        native engine reduces in tree order; the reference's
        ReduceHandle implicitly assumes commutativity too).  Engines
        with a native custom path override this.
        """
        if prepare_fun is not None:
            prepare_fun()
        if self.world_size == 1:
            return buf
        parts = self.allgather(buf)
        acc = np.array(parts[0], copy=True)
        for r in range(1, self.world_size):
            reducer(acc, parts[r])
        buf[...] = acc
        return buf

    # ---- checkpointing --------------------------------------------------
    @abstractmethod
    def load_checkpoint(self) -> tuple[int, Optional[bytes], Optional[bytes]]:
        """Return (version, global_model_bytes, local_model_bytes).

        version==0 means fresh start (no checkpoint exists)
        (reference: IEngine::LoadCheckPoint, src/allreduce_robust.cc:159-196).
        """

    @abstractmethod
    def checkpoint(
        self,
        global_model: bytes,
        local_model: Optional[bytes] = None,
        lazy_global: Optional[Callable[[], bytes]] = None,
    ) -> None:
        """Commit a checkpoint and bump the version.

        ``lazy_global`` implements LazyCheckPoint: when given (and
        ``global_model`` is None) serialization is deferred until a peer
        actually needs the payload during recovery
        (reference: src/allreduce_robust.h:125-127, allreduce_robust.cc:744-751).
        """

    @property
    @abstractmethod
    def version_number(self) -> int:
        """Checkpoint version counter (reference: IEngine::VersionNumber)."""

    # ---- observability --------------------------------------------------
    def stats(self) -> dict:
        """Snapshot of this engine's telemetry metrics
        (``{"counters": .., "gauges": .., "histograms": ..}`` — see
        :class:`rabit_tpu.obs.Metrics`).  Engines instrumented by the
        telemetry subsystem override this; the default (and any engine
        running with telemetry disabled) reports nothing."""
        return {}

    def events(self) -> list[dict]:
        """Structured event trace (op spans, link errors, recovery
        phases, checkpoint commits) as a list of dicts — the ring
        buffer of :class:`rabit_tpu.obs.EventTrace`.  Empty for
        uninstrumented engines or when telemetry is disabled."""
        return []

    def metrics(self):
        """The engine's LIVE :class:`rabit_tpu.obs.Metrics` registry,
        or ``None`` when telemetry is off.  App-layer subsystems (the
        serving plane's ``serve.*`` instruments — doc/serving.md) file
        their counters/gauges/histograms here so they ride the same
        streamed delta frames, shutdown summary and tracker
        ``/metrics`` exposition as the engine's own — one telemetry
        plane, not two."""
        if not getattr(self, "_obs_on", False):
            return None
        return getattr(self, "_metrics", None)

    def tracker_print(self, msg: str) -> None:
        """Ship a log line to the job's single logging point.

        The reference forwards *any* rank's message to the tracker
        (reference: IEngine::TrackerPrint, src/allreduce_base.cc:97-105);
        engines with a live tracker connection override this.  The default
        prints locally, rank-tagged when distributed.
        """
        if self.is_distributed():
            print(f"@tracker[{self.rank}] {msg}", flush=True)
        else:
            print(msg, flush=True)
