"""Pure-Python TCP collective engine (the "base engine").

TPU-native rebuild of the reference's non-fault-tolerant base engine
(reference: src/allreduce_base.{h,cc}): tracker rendezvous, persistent
worker-worker links, and the core collectives.  This is the DCN/host
fallback transport and the substrate the robust layer builds on; the C++
native engine implements the same wire behaviour for the performance path,
and the XLA engine replaces the data plane entirely with ICI collectives.

Algorithmic departures from the reference (deliberate):

* Large allreduces use **ring reduce-scatter + all-gather** (bandwidth
  optimal, every link equally loaded) instead of the reference's pipelined
  binary tree (src/allreduce_base.cc:326-491); small payloads use the tree
  (log₂n hops beats n hops on latency).
* Any-root broadcast is a plain tree flood: the root sends on all its tree
  links, everyone else forwards from their first-arriving link to the rest
  — same idea as the reference's in-link probing (src/allreduce_base.cc:
  500-588) without the slot machinery.
"""
from __future__ import annotations

import os
import select
import socket
import struct
import time
from typing import Callable, Optional

import numpy as np

from rabit_tpu import obs
from rabit_tpu.engine.interface import Engine
from rabit_tpu.ops import ReduceOp
from rabit_tpu.ops.reduce_ops import apply_op_numpy
from rabit_tpu.tracker import protocol as P
from rabit_tpu.utils.checks import check
from rabit_tpu.utils.units import parse_byte_size

# Payloads at or below this ride the tree (latency-bound); above, the ring
# (bandwidth-bound).
TREE_RING_CROSSOVER_BYTES = 64 << 10
# Chunk size for full-duplex streaming on the ring.
CHUNK_BYTES = 256 << 10


class LinkError(ConnectionError):
    """A worker-worker or tracker link failed (peer death or reset)."""


class PySocketEngine(Engine):
    def __init__(self) -> None:
        self._rank = 0
        self._world = 1
        self._links: dict[int, socket.socket] = {}
        self._tree_links: list[int] = []
        self._parent = P.NONE
        self._ring_prev = P.NONE
        self._ring_next = P.NONE
        self._tracker_addr: Optional[tuple[str, int]] = None
        self._task_id = "0"
        self._listener: Optional[socket.socket] = None
        self._version = 0
        self._global: Optional[bytes] = None
        self._local: Optional[bytes] = None
        self._timeout = 600.0  # overridden in init()
        self._relaunched = False
        # Telemetry (rabit_tpu.obs): off until init() resolves the
        # config; every call site gates on the single _obs_on bool so
        # the disabled cost is one attribute check per collective.
        self._obs_on = False
        self._obs_dir: Optional[str] = None
        self._metrics: Optional[obs.Metrics] = None
        self._trace: Optional[obs.EventTrace] = None
        self._log = obs.log.Logger(self._obs_role(),
                                   lambda: {"rank": self._rank})

    def _obs_role(self) -> str:
        return "pysocket"

    # ------------------------------------------------------------------
    # lifecycle / rendezvous
    # ------------------------------------------------------------------
    def init(self, params: dict) -> None:
        uri = params.get("rabit_tracker_uri") or os.environ.get("RABIT_TRACKER_URI")
        port = params.get("rabit_tracker_port") or os.environ.get("RABIT_TRACKER_PORT")
        check(uri is not None and port is not None,
              "pysocket engine needs rabit_tracker_uri/rabit_tracker_port")
        self._tracker_addr = (str(uri), int(port))
        self._task_id = str(params.get("rabit_task_id")
                            or os.environ.get("RABIT_TASK_ID", "0"))
        self._world_hint = int(params.get("rabit_world_size")
                               or os.environ.get("RABIT_WORLD_SIZE", 0))
        # Peer-link IO timeout: a hung-but-alive peer surfaces as
        # LinkError (-> recovery) after this long instead of wedging the
        # job for the old hard-coded 600 s (reference analogue: errno
        # classification, src/allreduce_base.cc:392-397).  Tracker waits
        # keep their own generous bound — barrier waits are legitimately
        # long while a dead rank restarts.
        self._timeout = float(params.get("rabit_timeout_sec")
                              or os.environ.get("RABIT_TIMEOUT_SEC", 600))
        if self._timeout <= 0:
            self._timeout = None  # <=0 disables the timeout (like native)
        # Collective scratch budget: payloads larger than this stream
        # through the tree/ring in budget-sized chunks, so per-op scratch
        # is bounded by configuration, not payload size (reference:
        # rabit_reduce_buffer, src/allreduce_base.cc:31,117-132).
        self._reduce_buffer = parse_byte_size(
            params.get("rabit_reduce_buffer")
            or os.environ.get("RABIT_REDUCE_BUFFER", "256MB"))
        self.scratch_peak_bytes = 0
        cfg = obs.configure(params)
        self._obs_on = cfg.enabled
        self._obs_dir = cfg.obs_dir
        self._metrics = obs.Metrics()
        self._trace = obs.EventTrace(capacity=cfg.trace_capacity)
        self._rendezvous(P.CMD_START)

    # Lower bound for waits on a REGISTERED tracker socket: rendezvous
    # replies legitimately wait out a dead rank's restart, so the
    # barrier keeps a generous floor even when rabit_timeout_sec is
    # tuned aggressively low for fast hung-peer detection.
    TRACKER_BARRIER_MIN_SEC = 600.0

    def _tracker_connect(self, cmd: str) -> socket.socket:
        # Connection ESTABLISHMENT honors rabit_timeout_sec (a dead or
        # unreachable tracker fails fast, like the link IO path); the
        # barrier wait after registration keeps its own generous bound.
        sock = socket.create_connection(self._tracker_addr,
                                        timeout=self._timeout)
        sock.settimeout(None if self._timeout is None
                        else max(self._timeout, self.TRACKER_BARRIER_MIN_SEC))
        P.send_u32(sock, P.MAGIC)
        P.send_str(sock, cmd)
        P.send_str(sock, self._task_id)
        P.send_u32(sock, self._world_hint)
        return sock

    def _rendezvous(self, cmd: str) -> None:
        """Register with the tracker, receive topology, wire up links."""
        self._close_links()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(64)
        my_port = self._listener.getsockname()[1]
        my_host = self._advertised_host()

        sock = self._tracker_connect(cmd)
        P.send_str(sock, my_host)
        P.send_u32(sock, my_port)
        topo = P.TopologyReply.recv(sock)
        sock.close()

        self._rank = topo.rank
        self._world = topo.world
        self._relaunched = self._relaunched or bool(topo.relaunched)
        self._parent = topo.parent
        self._tree_links = list(topo.neighbors)
        self._ring_prev = topo.ring_prev
        self._ring_next = topo.ring_next
        os.environ["RABIT_TPU_LOG_TAG"] = f"rank{self._rank}"

        # Outgoing links (to lower ranks, already listening).
        for peer_rank, host, port in topo.connect:
            # Peer connect honors rabit_timeout_sec like the link IO
            # path (the old hardcoded 600 s wedged recovery rounds when
            # a peer died between tracker reply and link wiring).
            s = socket.create_connection((host, port),
                                         timeout=self._timeout)
            s.settimeout(self._timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            P.send_u32(s, P.MAGIC)
            P.send_u32(s, self._rank)
            check(P.recv_u32(s) == P.MAGIC, "link handshake: bad magic")
            got = P.recv_u32(s)
            check(got == peer_rank, "link handshake: rank mismatch")
            self._links[peer_rank] = s
        # Incoming links (from higher ranks).  Bounded like the
        # outgoing dial: a peer that died between its tracker reply and
        # dialing us must surface as a timeout (-> rendezvous retry /
        # fail-fast), not an unbounded accept() wedge.
        self._listener.settimeout(self._timeout)
        for _ in range(topo.naccept):
            s, _addr = self._listener.accept()
            s.settimeout(self._timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            check(P.recv_u32(s) == P.MAGIC, "link handshake: bad magic")
            peer_rank = P.recv_u32(s)
            P.send_u32(s, P.MAGIC)
            P.send_u32(s, self._rank)
            self._links[peer_rank] = s
        self._listener.close()
        self._listener = None

    def _advertised_host(self) -> str:
        # Single-host jobs (tests, local launcher) rendezvous via loopback;
        # multi-host workers advertise the interface that routes to the
        # tracker.
        from rabit_tpu.utils.net import routable_ip

        return routable_ip(self._tracker_addr)

    def _close_links(self) -> None:
        for s in self._links.values():
            try:
                s.close()
            except OSError:
                pass
        self._links.clear()
        if self._listener is not None:
            self._listener.close()
            self._listener = None

    def shutdown(self) -> None:
        self._obs_flush()
        if self._tracker_addr is not None:
            try:
                sock = self._tracker_connect(P.CMD_SHUTDOWN)
                sock.close()
            except OSError as e:
                self._log.debug("shutdown notify failed (tracker gone?): %s",
                                e)
        self._close_links()

    # ------------------------------------------------------------------
    # telemetry (rabit_tpu.obs)
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        if not self._obs_on or self._metrics is None:
            return {}  # disabled telemetry reports nothing (interface.py)
        return self._metrics.snapshot()

    def events(self) -> list[dict]:
        return self._trace.events() if self._trace is not None else []

    def _op_seqno(self) -> Optional[int]:
        """Robust-protocol seqno for op events (None on the base engine,
        which has no op numbering)."""
        return None

    def _op_done(self, kind: str, nbytes: int, t0: float,
                 replayed: bool = False) -> None:
        """Record one completed collective (call sites gate on _obs_on)."""
        obs.record_op(self._metrics, self._trace, kind, nbytes,
                      time.perf_counter() - t0, self._rank,
                      seqno=self._op_seqno(), version=self._version,
                      replayed=replayed)

    def _obs_flush(self) -> None:
        """Ship the rank-local summary to the tracker's obs channel and
        dump the event trace under rabit_obs_dir (both best-effort; runs
        once, at the head of shutdown)."""
        if not self._obs_on:
            return
        if self._tracker_addr is not None and self._world > 1:
            obs.ship_summary(
                self.tracker_print, self._log, type(self).__name__,
                self._rank, self._world, self._metrics.snapshot(),
                [e for e in self._trace.events() if e.get("name") != "op"])
        if self._obs_dir:
            obs.dump_events(self._log, self._obs_dir, self._rank,
                            self._trace.events())

    # ------------------------------------------------------------------
    # identity
    # ------------------------------------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world

    @property
    def was_relaunched(self) -> bool:
        return self._relaunched

    def tracker_print(self, msg: str) -> None:
        sock = self._tracker_connect(P.CMD_PRINT)
        P.send_str(sock, msg)
        sock.close()

    # ------------------------------------------------------------------
    # link IO helpers
    # ------------------------------------------------------------------
    def _send(self, rank: int, data: bytes | memoryview) -> None:
        try:
            self._links[rank].sendall(data)
        except OSError as e:
            raise LinkError(f"send to rank {rank} failed: {e}") from e

    def _recv(self, rank: int, nbytes: int, into: memoryview | None = None):
        sock = self._links[rank]
        buf = into if into is not None else memoryview(bytearray(nbytes))
        got = 0
        try:
            while got < nbytes:
                n = sock.recv_into(buf[got:nbytes], nbytes - got)
                if n == 0:
                    raise LinkError(f"rank {rank} closed the link")
                got += n
        except OSError as e:
            raise LinkError(f"recv from rank {rank} failed: {e}") from e
        return buf

    def _exchange(self, send_rank: int, send_data: memoryview,
                  recv_rank: int, recv_buf: memoryview) -> None:
        """Full-duplex: stream send_data to one peer while filling recv_buf
        from another — avoids ring deadlock without threads."""
        ssock = self._links[send_rank]
        rsock = self._links[recv_rank]
        sent, got = 0, 0
        nsend, nrecv = len(send_data), len(recv_buf)
        ssock.setblocking(False)
        rsock.setblocking(False)
        try:
            while sent < nsend or got < nrecv:
                rlist = [rsock] if got < nrecv else []
                wlist = [ssock] if sent < nsend else []
                readable, writable, _ = select.select(rlist, wlist, [],
                                                      self._timeout)
                if not readable and not writable:
                    raise LinkError("exchange: timed out")
                if readable:
                    n = rsock.recv_into(recv_buf[got:], nrecv - got)
                    if n == 0:
                        raise LinkError(f"rank {recv_rank} closed the link")
                    got += n
                if writable:
                    sent += ssock.send(send_data[sent:sent + CHUNK_BYTES])
        except OSError as e:
            raise LinkError(f"exchange with {send_rank}/{recv_rank} failed: {e}") from e
        finally:
            # settimeout (not setblocking) — setblocking(True) would
            # clear the link IO timeout set at rendezvous
            ssock.settimeout(self._timeout)
            rsock.settimeout(self._timeout)

    # ------------------------------------------------------------------
    # collectives
    # ------------------------------------------------------------------
    def allreduce(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
    ) -> np.ndarray:
        if prepare_fun is not None:
            prepare_fun()
        if self._world == 1:
            return buf
        if not self._obs_on:
            self._allreduce_impl(buf, op)
            return buf
        t0 = time.perf_counter()
        self._allreduce_impl(buf, op)
        self._op_done("allreduce", buf.nbytes, t0)
        return buf

    def _allreduce_impl(self, buf: np.ndarray, op: ReduceOp) -> None:
        """Uninstrumented tree/ring dispatch (shared with the robust
        layer's retry path, which does its own accounting)."""
        if buf.nbytes <= TREE_RING_CROSSOVER_BYTES or self._world == 2:
            self._tree_allreduce(buf, op)
        else:
            self._ring_allreduce(buf, op)

    def _children(self) -> list[int]:
        return [r for r in self._tree_links if r != self._parent]

    def _note_scratch(self, nbytes: int) -> None:
        if nbytes > self.scratch_peak_bytes:
            self.scratch_peak_bytes = nbytes

    def _tree_chunked(self, view: memoryview, nitems: int, item: int,
                      merge) -> None:
        """Two-phase chunked tree collective, shared by the built-in and
        custom allreduce paths.

        Chunked to the rabit_reduce_buffer budget in two strictly
        one-directional phases (all chunks up, then all chunks down):
        blocking sockets cannot deadlock, chunks stream across tree
        levels, and the per-link byte stream matches the unchunked
        protocol, so peers with different budgets interoperate.
        ``merge(off, n, src)`` folds ``n`` items of received bytes
        ``src`` into the payload at item offset ``off``.
        """
        chunk = min(max(self._reduce_buffer // item, 1), nitems)
        scratch = memoryview(bytearray(chunk * item))
        self._note_scratch(len(scratch))
        children = self._children()
        # Phase 1: reduce up.
        for off in range(0, nitems, chunk):
            n = min(chunk, nitems - off)
            for child in children:
                self._recv(child, n * item, scratch[: n * item])
                merge(off, n, scratch[: n * item])
            if self._parent != P.NONE:
                self._send(self._parent, view[off * item:(off + n) * item])
        # Phase 2: broadcast down.
        for off in range(0, nitems, chunk):
            n = min(chunk, nitems - off)
            if self._parent != P.NONE:
                self._recv(self._parent, n * item,
                           view[off * item:(off + n) * item])
            for child in children:
                self._send(child, view[off * item:(off + n) * item])

    def _tree_allreduce(self, buf: np.ndarray, op: ReduceOp) -> None:
        """Reduce up the binary tree, broadcast the result down."""
        flat = buf.reshape(-1)
        if flat.nbytes == 0:
            return  # zero-size payloads move no wire bytes on any rank

        def merge(off: int, n: int, src: memoryview) -> None:
            apply_op_numpy(op, flat[off:off + n],
                           np.frombuffer(src, dtype=flat.dtype, count=n))

        self._tree_chunked(memoryview(flat).cast("B"), len(flat),
                           flat.itemsize, merge)

    def _ring_allreduce(self, buf: np.ndarray, op: ReduceOp) -> None:
        """Bandwidth-optimal ring: reduce-scatter then all-gather."""
        n = self._world
        flat = buf.reshape(-1)
        view = memoryview(flat).cast("B")
        nbytes = flat.nbytes
        # Block b covers bytes [off[b], off[b+1]); blocks itemsize-aligned.
        item = flat.itemsize
        per = (len(flat) + n - 1) // n
        bounds = [min(i * per, len(flat)) for i in range(n + 1)]

        def block(i: int) -> memoryview:
            b = i % n
            return view[bounds[b] * item: bounds[b + 1] * item]

        # Reduce-scatter scratch is one ring block, capped at the
        # rabit_reduce_buffer budget: oversized blocks stream through the
        # exchange in budget-sized sub-chunks (TCP framing is
        # size-agnostic, so peers with different budgets interoperate).
        chunk_elems = min(max(self._reduce_buffer // item, 1), per)
        scratch = np.empty(chunk_elems, dtype=flat.dtype)
        self._note_scratch(scratch.nbytes)
        # Phase 1: reduce-scatter.  After step s, block (rank-s) has been
        # combined at this rank with s+1 contributions.
        for s in range(n - 1):
            send_b = self._rank - s
            recv_b = self._rank - s - 1
            sblk, rblk = block(send_b), block(recv_b)
            slen, rlen = len(sblk), len(rblk)
            relem0 = bounds[recv_b % n]
            coff = 0
            while coff == 0 or coff < max(slen, rlen):
                sl = min(chunk_elems * item, max(slen - coff, 0))
                rl = min(chunk_elems * item, max(rlen - coff, 0))
                sview = memoryview(scratch).cast("B")[:rl]
                self._exchange(self._ring_next, sblk[coff:coff + sl],
                               self._ring_prev, sview)
                nelem = rl // item
                e0 = relem0 + coff // item
                apply_op_numpy(op, flat[e0:e0 + nelem], scratch[:nelem])
                coff += chunk_elems * item
        # Phase 2: all-gather the fully reduced blocks around the ring.
        for s in range(n - 1):
            send_b = self._rank + 1 - s
            recv_b = self._rank - s
            self._exchange(self._ring_next, block(send_b),
                           self._ring_prev, block(recv_b))

    def allreduce_custom(self, buf: np.ndarray, reducer, prepare_fun=None
                         ) -> np.ndarray:
        """Tree-fold custom allreduce: the Python ``reducer(dst, src)``
        merges per tree edge, O(log n) payload hops — replacing the
        interface's allgather-and-fold default (O(world x payload)), and
        matching the native engine's TreeAllreduceFn shape on the wire
        (reference analogue: ReduceHandle, include/rabit/engine.h:
        215-253).  Chunked row-wise to the reduce-buffer budget like
        _tree_allreduce; the reducer must be associative+commutative
        (merge order is tree order).
        """
        if prepare_fun is not None:
            prepare_fun()
        if self._world == 1:
            return buf
        if not self._obs_on:
            return self._allreduce_custom_impl(buf, reducer)
        t0 = time.perf_counter()
        out = self._allreduce_custom_impl(buf, reducer)
        self._op_done("allreduce_custom", buf.nbytes, t0)
        return out

    def _allreduce_custom_impl(self, buf: np.ndarray, reducer) -> np.ndarray:
        rows = buf.shape[0] if buf.ndim > 0 else buf.size
        check(rows > 0, "allreduce_custom: empty buffer")
        if buf.nbytes == 0:
            return buf  # zero-size rows: nothing to merge or move
        row_shape = buf.shape[1:] if buf.ndim > 1 else ()
        flat = buf.reshape(rows, -1)
        item = flat.shape[1] * flat.itemsize  # bytes per axis-0 row
        dst_rows = buf.reshape((rows,) + row_shape)

        def merge(off: int, n: int, src: memoryview) -> None:
            rows_in = np.frombuffer(src, dtype=buf.dtype,
                                    count=n * flat.shape[1])
            reducer(dst_rows[off:off + n], rows_in.reshape((n,) + row_shape))

        self._tree_chunked(memoryview(flat).cast("B"), rows, item, merge)
        return buf

    def broadcast(self, data: Optional[bytes], root: int) -> bytes:
        if self._world == 1:
            check(data is not None, "broadcast: root rank must supply data")
            return data
        if not self._obs_on:
            return self._bcast_impl(data, root)
        t0 = time.perf_counter()
        out = self._bcast_impl(data, root)
        self._op_done("broadcast", len(out), t0)
        return out

    def _bcast_impl(self, data: Optional[bytes], root: int) -> bytes:
        """Uninstrumented tree flood (also the robust layer's recovery
        serving transport, which must not count as a user op)."""
        if self._rank == root:
            check(data is not None, "broadcast: root rank must supply data")
            header = struct.pack("<Q", len(data))
            view = memoryview(data)
            for r in self._tree_links:
                self._send(r, header)
            for off in range(0, len(data), CHUNK_BYTES):
                chunk = view[off:off + CHUNK_BYTES]
                for r in self._tree_links:
                    self._send(r, chunk)
            return data
        # Non-root: the payload arrives on exactly one tree link — the
        # first hop on the tree path toward the root, computable locally
        # (no probing needed, unlike the reference's in-link slot scan).
        # Chunk-pipelined: each chunk is forwarded downstream as soon as
        # it arrives, so the payload streams through the tree instead of
        # paying full-payload latency per level (same idea as the
        # reference's per-link ring buffers, src/allreduce_base.cc:
        # 500-588; byte stream per link is unchanged).
        src = self._toward(root)
        raw = self._recv(src, 8)
        (size,) = struct.unpack("<Q", bytes(raw))
        payload = memoryview(bytearray(size))
        header = struct.pack("<Q", size)
        downstream = [r for r in self._tree_links if r != src]
        for r in downstream:
            self._send(r, header)
        for off in range(0, size, CHUNK_BYTES):
            end = min(off + CHUNK_BYTES, size)
            self._recv(src, end - off, payload[off:end])
            for r in downstream:
                self._send(r, payload[off:end])
        return bytes(payload)

    def _toward(self, root: int) -> int:
        """First hop on the binary-heap-tree path from this rank to ``root``.

        Walk ``root``'s ancestor chain (indices strictly decrease); if it
        passes through this rank, the hop is the child we came through,
        else it is our parent.
        """
        r, prev = root, P.NONE
        while r > self._rank:
            prev = r
            r = (r - 1) // 2
        return prev if r == self._rank else self._parent

    def allgather(self, buf: np.ndarray) -> np.ndarray:
        if self._world == 1:
            return buf[None]
        if not self._obs_on:
            return self._allgather_impl(buf)
        t0 = time.perf_counter()
        out = self._allgather_impl(buf)
        self._op_done("allgather", out.nbytes, t0)
        return out

    def _allgather_impl(self, buf: np.ndarray) -> np.ndarray:
        """Ring all-gather: n-1 steps, each forwarding the newest block."""
        n = self._world
        out = np.empty((n,) + buf.shape, dtype=buf.dtype)
        out[self._rank] = buf
        for s in range(n - 1):
            send_b = (self._rank - s) % n
            recv_b = (self._rank - s - 1) % n
            self._exchange(
                self._ring_next, memoryview(out[send_b]).cast("B"),
                self._ring_prev, memoryview(out[recv_b]).cast("B"))
        return out

    # ------------------------------------------------------------------
    # checkpoints (non-fault-tolerant: process-local, like the reference
    # base engine — the robust layer adds replication/recovery)
    # ------------------------------------------------------------------
    def load_checkpoint(self):
        return (self._version, self._global, self._local)

    def checkpoint(self, global_model, local_model=None, lazy_global=None):
        if global_model is None and lazy_global is not None:
            global_model = lazy_global()
        self._global = global_model
        self._local = local_model
        self._version += 1

    @property
    def version_number(self) -> int:
        return self._version
