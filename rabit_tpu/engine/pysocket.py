"""Pure-Python TCP collective engine (the "base engine").

TPU-native rebuild of the reference's non-fault-tolerant base engine
(reference: src/allreduce_base.{h,cc}): tracker rendezvous, persistent
worker-worker links, and the core collectives.  This is the DCN/host
fallback transport and the substrate the robust layer builds on; the C++
native engine implements the same wire behaviour for the performance path,
and the XLA engine replaces the data plane entirely with ICI collectives.

Algorithmic departures from the reference (deliberate):

* Large allreduces use **ring reduce-scatter + all-gather** (bandwidth
  optimal, every link equally loaded) instead of the reference's pipelined
  binary tree (src/allreduce_base.cc:326-491); small payloads use the tree
  (log₂n hops beats n hops on latency).
* Any-root broadcast is a plain tree flood: the root sends on all its tree
  links, everyone else forwards from their first-arriving link to the rest
  — same idea as the reference's in-link probing (src/allreduce_base.cc:
  500-588) without the slot machinery.
"""
from __future__ import annotations

import collections
import json
import os
import random
import select
import signal
import socket
import struct
import threading
import time
from typing import Callable, Optional

import numpy as np

from rabit_tpu import chaos as chaos_mod
from rabit_tpu import codec as codec_mod
from rabit_tpu import obs
from rabit_tpu.codec import kernel as ck_mod
from rabit_tpu import sched as sched_mod
from rabit_tpu import transport as tr
from rabit_tpu.engine.interface import (AsyncOrderError, CollectiveHandle,
                                        Engine)
from rabit_tpu.ops import ReduceOp
from rabit_tpu.ops.reduce_ops import apply_op_numpy
from rabit_tpu.tracker import protocol as P
from rabit_tpu.transport import IntegrityError, LinkError
from rabit_tpu.utils.checks import RabitError, check
from rabit_tpu.utils.units import parse_byte_size

# Payloads at or below this ride the tree (latency-bound); above, the ring
# (bandwidth-bound).  This module global is the DEFAULT for the static
# crossover; rabit_ring_threshold_bytes overrides it per engine, and
# rabit_sched replaces the whole static dispatch with forced or
# auto-tuned schedule selection (doc/performance.md).
TREE_RING_CROSSOVER_BYTES = 64 << 10
# Chunk size for full-duplex streaming on the ring.
CHUNK_BYTES = 256 << 10
# Hop-pipeline chunk FLOOR (rabit_pipeline_chunk): a pipelined hop
# splits each reduce-buffer chunk ``depth`` ways but never below this —
# every chunk boundary is a synchronization point (a pop is a per-chunk
# recv barrier), and on small hops the sync cost eats the overlap win,
# so hops that cannot produce at least two floor-sized chunks run the
# serial loop instead (doc/performance.md "Hop pipelining").
PIPE_CHUNK_BYTES = 64 << 10
# Default in-flight chunk window (rabit_pipeline_depth): 2 = classic
# double buffering — chunk k+1's exchange is on the wire while chunk k
# is merged.  1 = the legacy serial hop loop, byte- and bit-identical.
PIPE_DEPTH = 2
# Async small-op coalescing budget (rabit_bucket_bytes): same-op/same-dtype
# allreduces at or below this size fuse into one wire op.
DEFAULT_BUCKET_BYTES = 1 << 20


# LinkError/IntegrityError live in rabit_tpu.transport.base now (every
# transport raises them); re-imported above so the historical
# `from rabit_tpu.engine.pysocket import LinkError` spelling — used by
# the robust layer, tests and downstream code — keeps working.


class AdmissionError(LinkError):
    """The tracker refused this job's registration across the full
    admission retry budget (multi-tenant admission control:
    ``--max-jobs`` / ``--max-total-workers``, doc/fault_tolerance.md
    "Multi-tenant tracker").

    An over-capacity submission is not an outage: each rejection is a
    typed wire reply, the worker backs off and re-registers
    (``rabit_admission_retries``), and the tracker re-admits the moment
    a finishing job drains — so a submission racing a completing job
    gets in.  Only when every attempt is refused does this escape,
    carrying the tracker's last ``code``/``reason``.  A LinkError like
    :class:`TrackerLostError`: overload degrades to a typed failure,
    never a hang."""

    def __init__(self, msg: str, code: int = 0, reason: str = "") -> None:
        super().__init__(msg)
        self.code = int(code)
        self.reason = reason


class ShardMovedError(LinkError):
    """Every tracker the directory pointed at kept redirecting this
    job's registration elsewhere across the full ``rabit_shard_retries``
    budget (sharded control plane, doc/fault_tolerance.md "Sharded
    tracker").

    A single ``REJECT_SHARD_MOVED`` reply is not an error: the reason
    carries the owning shard's generation and endpoint, the worker
    re-targets and re-registers — one extra round trip, paid only when
    a cached directory went stale.  Redirects that keep chasing a
    moving owner past the budget mean the directory and the shards
    disagree persistently (split membership view, mid-rebalance churn);
    that surfaces here as a typed LinkError — the robust recover loop
    treats it like any dead link — carrying the last redirect's
    ``generation``, ``shard`` and ``endpoint``, so a postmortem can
    tell a stale cache (old generation, live endpoint) from a dead
    fleet (current generation, nothing answering)."""

    def __init__(self, msg: str, generation: int = -1,
                 shard: int = -1, endpoint: str = "") -> None:
        super().__init__(msg)
        self.generation = int(generation)
        self.shard = int(shard)
        self.endpoint = str(endpoint)


class TrackerLostError(LinkError):
    """The tracker stayed unreachable across the full registration
    retry budget — the job's coordinator is gone.

    A rendezvous registration (start/recover/rescale) retries the whole
    dial+register exchange with backoff, so a tracker merely
    *restarting* (crash + supervisor relaunch on the same port, its
    journal replayed — doc/fault_tolerance.md "Elastic membership &
    tracker HA") reads as a stall, never an error.  Only when every
    attempt fails does this escape: from ``init()`` it reaches the
    application directly; inside the robust engine's recover loop it is
    an ordinary link failure (this class IS a :class:`LinkError`) and
    surfaces wrapped in ``RecoveryError`` once the recover budget is
    also spent."""


class WorldChangedError(RabitError):
    """The world was rescaled out from under this collective/checkpoint.

    Raised (on every member, consistently) after an elastic membership
    epoch completes: the tracker reassigned ranks for a grown or shrunk
    world, so results, replay caches and rank-affine data shards from
    the old world are void.  The committed checkpoint is NOT lost — the
    contract is: catch this, call ``load_checkpoint()`` (served from
    the survivors' RAM replicas or the durable tier), re-shard
    rank-affine state for the new ``(rank, world)`` (e.g. with
    :func:`rabit_tpu.learn.splitrows.rows_for_rank`), and resume the
    loop from the returned version.  Carries ``old_world``,
    ``new_world`` and the new ``epoch``."""

    def __init__(self, old_world: int, new_world: int, epoch: int) -> None:
        super().__init__(
            f"world rescaled from {old_world} to {new_world} rank(s) "
            f"(membership epoch {epoch}): reload the last committed "
            f"checkpoint and re-shard rank-affine state")
        self.old_world = int(old_world)
        self.new_world = int(new_world)
        self.epoch = int(epoch)


class AsyncPumpError(RuntimeError):
    """The async progress pump died; queued collectives can never run.

    Raised at ``CollectiveHandle.wait()`` for every op that was queued
    behind (or issued after) the pump's death — the stream is poisoned
    so callers fail loudly instead of hanging on handles nobody will
    ever resolve."""


class _ScratchArena:
    """Pooled reusable byte buffers for the chunked collective paths.

    The tree/ring pumps borrow per-chunk scratch from here instead of
    allocating a fresh ``bytearray`` per call — on the small-op hot path
    (consensus words, bucketed streams) the allocator churn was
    measurable.  Buffers are handed out as exact-size memoryviews over a
    possibly-larger pooled backing store; the pool is bounded, so worst
    case memory is a few ``rabit_reduce_buffer`` chunks.
    """

    # Only small-to-middling buffers are worth retaining: the pool
    # exists for small-op allocator churn, and keeping multi-hundred-MB
    # tree leases alive for the engine's lifetime would trade transient
    # scratch for permanent retention.
    MAX_POOLED_BYTES = 4 << 20

    def __init__(self, max_buffers: int = 8) -> None:
        self._free: list[bytearray] = []
        self._max = max_buffers
        self._lock = threading.Lock()

    def take(self, nbytes: int) -> memoryview:
        with self._lock:
            for i, b in enumerate(self._free):
                if len(b) >= nbytes:
                    return memoryview(self._free.pop(i))[:nbytes]
        return memoryview(bytearray(max(nbytes, 1)))[:nbytes]

    def give(self, mv: memoryview) -> None:
        backing = mv.obj
        if not isinstance(backing, bytearray):
            return
        if len(backing) > self.MAX_POOLED_BYTES:
            return  # oversized lease: let the allocator reclaim it
        with self._lock:
            if len(self._free) < self._max:
                self._free.append(backing)


class _TransportEvents(tr.Events):
    """Transport-layer telemetry routed into the engine's obs plumbing
    (counters + trace events), gated on the single _obs_on bool like
    every other engine call site."""

    def __init__(self, eng: "PySocketEngine") -> None:
        self._eng = eng

    def counter(self, name: str, n: int = 1) -> None:
        eng = self._eng
        if eng._obs_on:
            eng._metrics.counter(name).inc(n)

    def event(self, name: str, **fields) -> None:
        eng = self._eng
        if eng._obs_on:
            eng._trace.emit(name, rank=eng._rank, **fields)


class PySocketEngine(Engine):
    def __init__(self) -> None:
        self._rank = 0
        self._world = 1
        self._links: dict[int, tr.Link] = {}
        self._tree_links: list[int] = []
        self._parent = P.NONE
        self._ring_prev = P.NONE
        self._ring_next = P.NONE
        self._tracker_addr: Optional[tuple[str, int]] = None
        self._task_id = "0"
        # Multi-tenant job id (rabit_job_id / RABIT_JOB_ID): names the
        # tenant on every tracker connection.  The default job speaks
        # the classic wire byte-for-byte (old trackers still work).
        self._job_id = P.DEFAULT_JOB
        self._listener: Optional[socket.socket] = None
        self._version = 0
        self._epoch = 0    # membership epoch of the current topology
        self._global: Optional[bytes] = None
        self._local: Optional[bytes] = None
        self._timeout = 600.0  # overridden in init()
        self._relaunched = False
        # Connect retry policy (rabit_connect_retries /
        # rabit_backoff_base_ms): capped exponential backoff with full
        # jitter, mirroring the native layer's ConnectRetry
        # (native/src/socket.cc) on every dial.
        self._connect_retries = 4
        self._backoff_base_ms = 100.0
        self._admission_retries = 10
        # Sharded control plane (rabit_directory): built in init().
        self._directory = None
        self._shard_retries = 4
        # Fault-injection plan (rabit_chaos); None = chaos off, and
        # every touchpoint gates on that single check.
        self._chaos: Optional[chaos_mod.ChaosPlan] = None
        self._sock_buf = 0          # rabit_sock_buf (0 = kernel default)
        # Pluggable transports (rabit_tpu/transport/): the factory owns
        # link construction + feature negotiation + shm failover
        # denial; built for real in init() once the knobs resolve.
        self._lf = tr.LinkFactory(tr.TransportConfig(),
                                  timeout=self._timeout)
        self._transport_label = "tcp"   # tuning-cache key dimension
        self._obs_transport = "tcp"     # LIVE label streamed to obs
        # Wire codec (rabit_wire_codec): the ONE lossy wire-format
        # seam — None is the classic full-width wire, Bf16Codec is the
        # historical rabit_wire_dtype=bf16 cast, the block-scaled
        # int8/int4 codecs quantize with error feedback.  _op_codec/
        # _op_cstate are the per-dispatch window the schedules' merge
        # seam (_wire_merge) consults; ops are serialized (the async
        # pump owns the links while handles are in flight), so one
        # slot suffices.
        self._codec: Optional[codec_mod.Codec] = None
        self._codec_label = "none"  # tuning-cache key dimension
        self._codec_block = codec_mod.DEFAULT_BLOCK
        self._codec_min_bytes = codec_mod.DEFAULT_MIN_BYTES
        # Directive codec overrides (doc/performance.md "Online
        # adaptation"): lazily-built codec instances for the per-bucket
        # ``bytes:sched/codec`` form of the controller's directive —
        # same replicated block/floor config as the job codec, so the
        # override stays a collective decision.
        self._codec_byname: dict[str, Optional[codec_mod.Codec]] = {}
        self._feedback = codec_mod.FeedbackBuffer()
        self._op_codec = None
        self._op_cstate = None
        # Compiled codec kernels (rabit_codec_impl, codec/kernel.py):
        # the block-scale hop math runs through librabit_codec.so when
        # it loads, numpy otherwise — bit-identical by contract, so
        # this is a per-rank perf knob like the pipeline depth, never
        # a collective decision.  _op_elem_k arms the native bf16
        # elementwise merge for one dispatch window; _op_ck_time
        # accumulates this op's codec kernel/hop-math seconds for the
        # obs plane (codec.kernel.seconds).
        self._codec_kernel: Optional[codec_mod.CodecKernel] = None
        self._codec_impl = "numpy"
        self._op_elem_k = None
        self._op_ck_time = 0.0
        self._bucket_bytes = DEFAULT_BUCKET_BYTES
        self._arena = _ScratchArena()
        # Hop pipelining (rabit_pipeline_depth / rabit_pipeline_chunk):
        # the schedules' chunked exchange+merge loops keep up to
        # _pipe_depth chunk exchanges in flight so merge compute hides
        # behind wire IO.  Depth 1 is the legacy serial loop; the wire
        # byte stream is depth-independent, so mixed-depth worlds
        # interoperate (doc/performance.md "Hop pipelining").
        self._pipe_depth = PIPE_DEPTH
        self._pipe_chunk = PIPE_CHUNK_BYTES
        # Collective schedule selection (rabit_sched): "static" keeps
        # the tree/ring crossover, "auto" consults the tuning cache, a
        # schedule name forces it wherever it applies.  The topology
        # handout's host groups feed the hierarchical schedule.
        self._sched_name = "static"
        self._ring_threshold: Optional[int] = None  # None -> module global
        self._tune_dir: Optional[str] = None
        self._tuner: Optional[sched_mod.TuningCache] = None
        self._groups: list[int] = []
        self._last_sched: Optional[str] = None  # trace on choice change
        # Live adaptation state from the topology handout (tracker
        # AdaptiveController, doc/performance.md "Online adaptation"):
        # a per-payload-bucket schedule directive consulted before the
        # static/auto pick, and the straggler-demoted ranks excluded
        # from hierarchical leadership.  Both land on EVERY rank in the
        # same rendezvous round, so dispatch stays a collective
        # decision.
        self._sched_live: dict[int, str] = {}
        self._demoted: frozenset = frozenset()
        # Async collective stream: a single background progress thread
        # (created lazily on the first *_async call) executes queued ops
        # strictly in issue order, so seqno/replay layers above see the
        # exact op sequence a blocking caller would produce.
        self._aq: collections.deque = collections.deque()
        self._aq_cv = threading.Condition()
        self._aq_thread: Optional[threading.Thread] = None
        self._aq_inflight = 0   # queued-but-unfinished op groups
        self._pump_error: Optional[Exception] = None  # pump died: poisoned
        self._issue_idx = 0     # async handles issued (user ops)
        self._wait_idx = 0      # next handle index allowed to wait()
        self._pending: Optional[dict] = None  # open coalescing bucket
        # Heartbeat liveness channel (rabit_heartbeat_sec): one
        # persistent tracker connection fed by a background thread so
        # the control plane learns about a hung/dead worker proactively
        # instead of waiting for a collective to touch the corpse.
        self._hb_sec = 0.0
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # Telemetry (rabit_tpu.obs): off until init() resolves the
        # config; every call site gates on the single _obs_on bool so
        # the disabled cost is one attribute check per collective.
        self._obs_on = False
        self._obs_dir: Optional[str] = None
        self._metrics: Optional[obs.Metrics] = None
        self._trace: Optional[obs.EventTrace] = None
        # Live telemetry plane (doc/observability.md "Live telemetry"):
        # with telemetry on and rabit_obs_flush_sec > 0, the heartbeat
        # thread ships one delta frame + the buffered collective spans
        # per flush period over the persistent heartbeat connection.
        self._obs_flush_sec = 0.0
        self._span_buf: Optional[obs.SpanBuffer] = None
        self._exporter: Optional[obs.DeltaExporter] = None
        self._span_seq = 0          # span seq fallback (no protocol seqno)
        self._op_sched: Optional[str] = None  # schedule of the last dispatch
        self._op_wire = "none"  # effective wire format of the last op
        # Causal hop tracing (doc/observability.md "Causal tracing &
        # postmortem"): rabit_trace_sample arms per-hop/per-chunk/codec
        # -window records on every Nth op — the decision is
        # deterministic in the op seqno, so all ranks trace the SAME
        # ops and the tracker assembles complete cross-rank timelines.
        # Off (_op_traced False, sample 0), every emit site is one
        # attribute check.
        self._trace_sample = 0
        self._hop_buf: Optional[obs.HopBuffer] = None
        self._op_traced = False
        self._op_trace_key: Optional[tuple] = None
        self._hop_idx = 0           # op-local hop index while traced
        self._op_count = 0          # lockstep op index (seqno fallback)
        # Flight recorder: the always-on crash ring; persists under
        # rabit_trace_dir on every fault path (LinkError escalation,
        # SIGTERM, recovery budget exhaustion).
        self._flight: Optional[obs.FlightRecorder] = None
        self._trace_dir: Optional[str] = None
        self._log = obs.log.Logger(self._obs_role(), self._log_ctx)

    def _obs_role(self) -> str:
        return "pysocket"

    def _log_ctx(self) -> dict:
        """Structured-log prefix: co-tenant jobs' merged stderr must be
        attributable, so a named job rides in every line."""
        if self._job_id != P.DEFAULT_JOB:
            return {"job": self._job_id, "rank": self._rank}
        return {"rank": self._rank}

    # ------------------------------------------------------------------
    # lifecycle / rendezvous
    # ------------------------------------------------------------------
    def init(self, params: dict) -> None:
        uri = params.get("rabit_tracker_uri") or os.environ.get("RABIT_TRACKER_URI")
        port = params.get("rabit_tracker_port") or os.environ.get("RABIT_TRACKER_PORT")
        check(uri is not None and port is not None,
              "pysocket engine needs rabit_tracker_uri/rabit_tracker_port")
        self._tracker_addr = (str(uri), int(port))
        self._task_id = str(params.get("rabit_task_id")
                            or os.environ.get("RABIT_TASK_ID", "0"))
        # Tenant identity (rabit_job_id / RABIT_JOB_ID): scopes every
        # tracker-side structure (rank map, barriers, heartbeats,
        # journal, obs dirs) to this job on a multi-tenant tracker.
        # Path-safe by contract — it names directories on the tracker.
        self._job_id = str(params.get("rabit_job_id")
                           or os.environ.get("RABIT_JOB_ID")
                           or P.DEFAULT_JOB)
        check(P.valid_job_id(self._job_id),
              "rabit_job_id must be a path-safe token "
              "([A-Za-z0-9][A-Za-z0-9._-]{0,63}), got %r", self._job_id)
        self._world_hint = int(params.get("rabit_world_size")
                               or os.environ.get("RABIT_WORLD_SIZE", 0))
        # Peer-link IO timeout: a hung-but-alive peer surfaces as
        # LinkError (-> recovery) after this long instead of wedging the
        # job for the old hard-coded 600 s (reference analogue: errno
        # classification, src/allreduce_base.cc:392-397).  Tracker waits
        # keep their own generous bound — barrier waits are legitimately
        # long while a dead rank restarts.
        self._timeout = float(params.get("rabit_timeout_sec")
                              or os.environ.get("RABIT_TIMEOUT_SEC", 600))
        if self._timeout <= 0:
            self._timeout = None  # <=0 disables the timeout (like native)
        # Collective scratch budget: payloads larger than this stream
        # through the tree/ring in budget-sized chunks, so per-op scratch
        # is bounded by configuration, not payload size (reference:
        # rabit_reduce_buffer, src/allreduce_base.cc:31,117-132).
        self._reduce_buffer = parse_byte_size(
            params.get("rabit_reduce_buffer")
            or os.environ.get("RABIT_REDUCE_BUFFER", "256MB"))
        self.scratch_peak_bytes = 0
        def _size_or_zero(raw, default: int) -> int:
            if raw is None or str(raw).strip() == "":
                return default
            if str(raw).strip() == "0":
                return 0  # explicit disable (parse_byte_size rejects 0)
            return parse_byte_size(raw)

        def _param_or_env(key: str):
            # `params.get(k) or env` would drop an explicit integer 0 —
            # the documented "disable" value — so test None, not truth.
            raw = params.get(key)
            return raw if raw is not None else os.environ.get(key.upper())

        # Small-op coalescing budget for the async path (0 disables
        # fusion; async ops still overlap).  Buckets are collective ops,
        # so this MUST be uniform across ranks — which is why it is
        # never derived from rank-local knobs like rabit_reduce_buffer
        # (doc/performance.md).
        self._bucket_bytes = _size_or_zero(
            _param_or_env("rabit_bucket_bytes"), DEFAULT_BUCKET_BYTES)
        # Socket buffer sizes (SO_SNDBUF/SO_RCVBUF) for worker-worker
        # links; 0 keeps the kernel default, which silently caps ring
        # throughput on fat links (doc/performance.md).
        self._sock_buf = _size_or_zero(_param_or_env("rabit_sock_buf"), 0)
        # Schedule selection (doc/performance.md "Schedule selection").
        # Like the bucket budget, BOTH knobs decide collective behaviour
        # and must be uniform across ranks: every rank dispatches the
        # same (op, size, world) point to the same algorithm or the
        # peer patterns deadlock.
        raw = _param_or_env("rabit_sched")
        self._sched_name = (str(raw).strip().lower()
                            if raw not in (None, "") else "static")
        check(self._sched_name in sched_mod.MODES,
              "rabit_sched must be one of %s, got %r",
              "/".join(sched_mod.MODES), self._sched_name)
        raw = _param_or_env("rabit_ring_threshold_bytes")
        self._ring_threshold = (None if raw in (None, "")
                                else _size_or_zero(raw, None))
        # Sketch plan for the synthesized schedule (sched/synth.py):
        # an optional plan JSON carrying link costs / chunk count and
        # optionally a precomputed cycle from the offline CLI.  Like
        # rabit_sched it decides collective behaviour: every rank must
        # load IDENTICAL plan content or the synthesized peer patterns
        # diverge and deadlock.
        raw = _param_or_env("rabit_synth_plan")
        self._synth_plan = (sched_mod.load_plan(str(raw))
                            if raw not in (None, "") else None)
        raw = _param_or_env("rabit_tune_dir")
        self._tune_dir = str(raw) if raw not in (None, "") else None
        self._tuner = None
        if self._sched_name == "auto":
            if self._tune_dir:
                self._tuner = sched_mod.TuningCache.load(self._tune_dir)
            if self._tuner is None:
                # Loud in both miss shapes — unset dir and unusable
                # cache — or the user has no signal the tuner never
                # engaged and every op quietly rides static.
                self._log.info(
                    "rabit_sched=auto: %s; falling back to the static "
                    "crossover",
                    f"no usable tuning cache under {self._tune_dir}"
                    if self._tune_dir else "rabit_tune_dir not set")
        # Optional lossy wire formats (doc/performance.md "Quantized
        # wire codecs"): rabit_wire_codec selects bf16 (half bytes,
        # the historical rabit_wire_dtype=bf16 cast — that alias keeps
        # working but is deprecated) or the block-scaled int8/int4
        # codecs (2-4x fewer wire bytes, error-feedback compensated).
        # Like the schedule knobs, ALL codec config decides collective
        # behaviour and must be uniform across ranks.
        wire = str(params.get("rabit_wire_dtype")
                   or os.environ.get("RABIT_WIRE_DTYPE", "native")).lower()
        check(wire in ("native", "bf16"),
              "rabit_wire_dtype must be 'native' or 'bf16', got %r", wire)
        raw = _param_or_env("rabit_codec_block")
        self._codec_block = (int(raw) if raw not in (None, "")
                             else codec_mod.DEFAULT_BLOCK)
        self._codec_min_bytes = _size_or_zero(
            _param_or_env("rabit_codec_min_bytes"),
            codec_mod.DEFAULT_MIN_BYTES)
        # Which IMPLEMENTATION runs the block-scale hop math: the
        # compiled kernels (native/src/codec_kernels.c via the ctypes
        # seam) or the numpy reference.  Bit-identical by contract
        # (tests/test_native_codec.py), so unlike every knob above this
        # is NOT a collective decision — ranks may mix freely, and
        # auto's fallback on a toolchain-free box changes nothing but
        # speed.  The resolved label (native / numpy / numpy-fallback)
        # is surfaced per rank in /status and rabit_top so a silent
        # degrade is visible in one glance.
        self._codec_kernel, self._codec_impl = codec_mod.resolve_impl(
            _param_or_env("rabit_codec_impl"), log=self._log)
        self._codec = codec_mod.resolve(
            _param_or_env("rabit_wire_codec"), wire,
            self._codec_block, self._codec_min_bytes, log=self._log,
            kernel=self._codec_kernel)
        self._codec_label = (self._codec.name if self._codec is not None
                             else "none")
        self._codec_byname = {self._codec_label: self._codec}
        self._feedback = codec_mod.FeedbackBuffer()
        # Hop pipelining (doc/performance.md "Hop pipelining"): depth 1
        # disables (the legacy serial hop loop); the wire byte stream
        # is depth-independent, so unlike the codec/schedule knobs this
        # is a per-rank perf knob, not a collective decision — though
        # uniform values give uniform timing.
        raw = _param_or_env("rabit_pipeline_depth")
        self._pipe_depth = int(raw) if raw not in (None, "") else PIPE_DEPTH
        check(1 <= self._pipe_depth <= 64,
              "rabit_pipeline_depth must be in [1, 64], got %r",
              self._pipe_depth)
        self._pipe_chunk = _size_or_zero(
            _param_or_env("rabit_pipeline_chunk"), PIPE_CHUNK_BYTES)
        check(self._pipe_chunk > 0,
              "rabit_pipeline_chunk must be > 0")
        # Connect retry policy: a refused/timed-out dial (a peer merely
        # slow to listen, a tracker restarting) is retried with capped
        # exponential backoff + full jitter instead of killing the
        # worker on the first SYN (native analogue: ConnectRetry,
        # native/src/socket.cc).
        raw = _param_or_env("rabit_connect_retries")
        self._connect_retries = int(raw) if raw not in (None, "") else 4
        check(self._connect_retries >= 0,
              "rabit_connect_retries must be >= 0")
        raw = _param_or_env("rabit_backoff_base_ms")
        self._backoff_base_ms = float(raw) if raw not in (None, "") else 100.0
        check(self._backoff_base_ms > 0, "rabit_backoff_base_ms must be > 0")
        # Admission retry budget: a typed admission reject (multi-tenant
        # tracker at capacity) is re-registered with backoff this many
        # extra times — long enough for a finishing co-tenant job to
        # drain and free the slot — before a typed AdmissionError.
        raw = _param_or_env("rabit_admission_retries")
        self._admission_retries = int(raw) if raw not in (None, "") else 10
        check(self._admission_retries >= 0,
              "rabit_admission_retries must be >= 0")
        # Sharded control plane (rabit_directory / RABIT_DIRECTORY):
        # with a job directory configured, a REJECT_SHARD_MOVED redirect
        # re-targets the owning shard, and a dead tracker address is
        # re-resolved through the directory before the dial budget is
        # spent — shard failover reads as a bounded stall.  Without it,
        # nothing changes: the single-tracker wire stays byte-identical.
        raw = _param_or_env("rabit_directory")
        self._directory = None
        if raw not in (None, ""):
            from rabit_tpu.tracker.directory import DirectoryClient
            self._directory = DirectoryClient(str(raw).strip())
        raw = _param_or_env("rabit_shard_retries")
        self._shard_retries = int(raw) if raw not in (None, "") else 4
        check(self._shard_retries >= 0,
              "rabit_shard_retries must be >= 0")
        # Proactive liveness: send one keepalive per rabit_heartbeat_sec
        # on a persistent tracker connection (0 disables; the tracker's
        # miss budget is rabit_heartbeat_miss periods — doc/
        # fault_tolerance.md "Durable checkpoints & heartbeats").
        raw = _param_or_env("rabit_heartbeat_sec")
        self._hb_sec = float(raw) if raw not in (None, "") else 0.0
        check(self._hb_sec >= 0, "rabit_heartbeat_sec must be >= 0")
        cfg = obs.configure(params)
        self._obs_on = cfg.enabled
        self._obs_dir = cfg.obs_dir
        self._metrics = obs.Metrics()
        self._trace = obs.EventTrace(capacity=cfg.trace_capacity)
        if cfg.enabled:
            self._obs_flush_sec = cfg.flush_sec
            self._span_buf = obs.SpanBuffer()
            self._exporter = obs.DeltaExporter(self._metrics)
            if cfg.trace_sample:
                # Hop records ride the streaming frames, so sampling
                # without the live plane would trace into a void.
                self._trace_sample = cfg.trace_sample
                self._hop_buf = obs.HopBuffer()
        # The flight recorder is ALWAYS on (a ring append per op is the
        # whole cost) — with rabit_trace_dir set, fault paths persist it
        # for tools/postmortem.py.
        self._trace_dir = cfg.trace_dir
        self._flight = obs.FlightRecorder(capacity=cfg.flight_events)
        self._install_flight_sigterm()
        # Deterministic fault injection (rabit_chaos): the plan wraps
        # every socket touchpoint from the first rendezvous on.
        self._chaos = chaos_mod.configure(params, identity=self._task_id,
                                          on_inject=self._chaos_inject)
        # Pluggable transports + integrity framing (doc/parameters.md
        # "Transports"; doc/fault_tolerance.md "Transports, integrity &
        # failover").  All defaults keep the wire byte-identical; every
        # feature is negotiated per link at rendezvous.
        raw = _param_or_env("rabit_transport")
        transport = (str(raw).strip().lower()
                     if raw not in (None, "") else "tcp")
        raw = _param_or_env("rabit_wire_integrity")
        integrity = (str(raw).strip().lower()
                     if raw not in (None, "") else "off")
        ring_bytes = _size_or_zero(
            _param_or_env("rabit_shm_ring_bytes"), 1 << 20) or (1 << 20)
        raw = _param_or_env("rabit_transport_failover")
        failover = str(raw).strip().lower() not in ("0", "false", "off") \
            if raw not in (None, "") else True
        raw = _param_or_env("rabit_shm_retries")
        shm_retries = int(raw) if raw not in (None, "") else 3
        raw = _param_or_env("rabit_shm_dir")
        shm_dir = str(raw) if raw not in (None, "") else None
        # Egress pacing (bench/test knob, doc/parameters.md): emulate a
        # constrained cross-host link budget on loopback so bandwidth-
        # regime measurements (wire codecs, schedule crossovers) run in
        # the regime they target.  0 (the default) = unpaced.
        raw = _param_or_env("rabit_link_mbps")
        link_mbps = float(raw) if raw not in (None, "") else 0.0
        cfg = tr.TransportConfig(
            transport=transport, integrity=integrity,
            shm_ring_bytes=ring_bytes, failover=failover,
            shm_retries=shm_retries, shm_dir=shm_dir,
            link_mbps=link_mbps)
        self._lf = tr.LinkFactory(
            cfg, timeout=self._timeout, sock_buf=self._sock_buf,
            chaos=self._chaos, wrap=self._wrap_link,
            events=_TransportEvents(self), log=self._log)
        self._rendezvous(P.CMD_START)
        self._start_heartbeat()

    # Lower bound for waits on a REGISTERED tracker socket: rendezvous
    # replies legitimately wait out a dead rank's restart, so the
    # barrier keeps a generous floor even when rabit_timeout_sec is
    # tuned aggressively low for fast hung-peer detection.
    TRACKER_BARRIER_MIN_SEC = 600.0

    # Exponential backoff doubles up to this many times, so the delay
    # cap is rabit_backoff_base_ms * 2**5 = 32x the base.
    BACKOFF_CAP_DOUBLINGS = 5

    def _chaos_inject(self, kind: str, site: str, ordinal: int,
                      detail: str) -> None:
        """Plan callback: every injected fault is logged and (with
        telemetry on) counted + traced, so the tracker's merged
        obs_report timeline can pair each fault with the retry/recovery
        it forced."""
        self._log.info("chaos: injected %s at %s (#%d, %s)",
                       kind, site, ordinal, detail)
        if self._obs_on:
            self._metrics.counter("chaos.injected").inc()
            self._metrics.counter(f"chaos.injected.{kind}").inc()
            self._trace.emit("chaos", kind=kind, site=site, rank=self._rank,
                             ordinal=ordinal)

    def _backoff_delay_ms(self, attempt: int) -> float:
        """One capped-exponential-full-jitter backoff step:
        uniform(0, min(base * 2**(attempt-1), 32 * base)).  Full jitter
        (not a fixed schedule) so a world of workers hammering one
        rendezvous point decorrelates instead of thundering in lockstep.
        """
        base = self._backoff_base_ms
        cap_ms = base * (1 << min(attempt - 1, self.BACKOFF_CAP_DOUBLINGS))
        return random.uniform(0.0, cap_ms)

    def _backoff(self, site: str, attempt: int,
                 err: Optional[Exception],
                 max_ms: Optional[float] = None) -> None:
        """Sleep one backoff step before a connect retry, under the
        dial-level ``net.*`` telemetry (recover-rendezvous pacing has
        its own instruments — see robust.py).  ``max_ms`` clamps the
        sleep to a caller's remaining time budget."""
        delay_ms = self._backoff_delay_ms(attempt)
        if max_ms is not None:
            delay_ms = min(delay_ms, max(max_ms, 0.0))
        if self._obs_on:
            self._metrics.counter("net.connect.retries").inc()
            self._metrics.histogram("net.backoff.seconds").observe(
                delay_ms / 1000.0)
            self._trace.emit("net", phase="backoff", site=site,
                             rank=self._rank, attempt=attempt,
                             delay_ms=round(delay_ms, 3),
                             error=type(err).__name__ if err else None)
        self._log.debug("connect to %s failed (%s); retry #%d after "
                        "%.0f ms", site, err, attempt, delay_ms)
        time.sleep(delay_ms / 1000.0)

    def _dial_retry(self, addr: tuple[str, int], site: str,
                    chaos: bool = True) -> socket.socket:
        """Dial with retries: up to rabit_connect_retries + 1 attempts,
        backed off between failures, within ONE rabit_timeout_sec of
        total wall time — retrying must never multiply how long a dead
        peer can wedge a rendezvous round (each attempt's connect
        timeout shrinks to the remaining budget, so SYN-dropped hosts
        still fail in one timeout like the un-retried dial did, while
        instantly-refused dials get every attempt).  Raises LinkError
        (an OSError) carrying the last failure once either budget is
        spent."""
        attempts = self._connect_retries + 1
        deadline = (None if self._timeout is None
                    else time.monotonic() + self._timeout)
        last: Optional[OSError] = None
        made = 0
        for attempt in range(attempts):
            if attempt:
                # Budget check BEFORE the sleep (a retry past the
                # deadline would neither sleep honestly nor dial), and
                # the sleep itself is clamped to what's left.
                left_ms = (None if deadline is None
                           else (deadline - time.monotonic()) * 1000.0)
                if left_ms is not None and left_ms <= 0:
                    break
                self._backoff(site, attempt, last, max_ms=left_ms)
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
            try:
                made += 1
                if chaos and self._chaos is not None:
                    self._chaos.connect(site)
                return socket.create_connection(addr, timeout=remaining)
            except OSError as e:
                last = e
                if self._obs_on:
                    self._metrics.counter("net.connect.failures").inc()
        raise LinkError(f"connect to {site} {addr[0]}:{addr[1]} failed "
                        f"after {made} attempt(s): {last}") from last

    def _redirect_tracker(self) -> bool:
        """Re-resolve this job's owning shard through the directory
        after a tracker failure; True when the target changed.  Covers
        every tracker touchpoint downstream of :meth:`_tracker_connect`
        — registrations, heartbeat re-dials, epoch polls and the
        shutdown goodbye all follow a shard failover to the survivor,
        so a handed-off job still closes its books as *finished*."""
        if self._directory is None:
            return False
        try:
            self._directory.invalidate()
            owner = self._directory.owner(self._job_id)
        except (OSError, ValueError) as e:
            self._log.debug("directory re-resolve failed: %s", e)
            return False
        if owner is None:
            return False
        idx, host, port = owner
        if (host, port) == self._tracker_addr:
            return False
        self._log.info("directory: job %r now owned by shard %d at "
                       "%s:%d", self._job_id, idx, host, port)
        if self._obs_on:
            self._metrics.counter("net.tracker.redirects").inc()
        self._tracker_addr = (host, port)
        return True

    def _tracker_connect(self, cmd: str, chaos: bool = True) -> socket.socket:
        # Connection ESTABLISHMENT honors rabit_timeout_sec (a dead or
        # unreachable tracker fails fast, like the link IO path) and
        # retries with backoff; the barrier wait after registration
        # keeps its own generous bound.  ``chaos=False`` exempts a dial
        # from fault injection: the heartbeat thread's dials interleave
        # nondeterministically with the op stream, and letting them
        # consult the plan would break the seed-replay contract.
        try:
            sock = self._dial_retry(self._tracker_addr,
                                    chaos_mod.SITE_TRACKER, chaos=chaos)
        except LinkError:
            # The shard may be dead, not restarting: ask the directory
            # who owns the job now, then spend one more dial budget on
            # the survivor.  Without a directory the failure stands.
            if not self._redirect_tracker():
                raise
            sock = self._dial_retry(self._tracker_addr,
                                    chaos_mod.SITE_TRACKER, chaos=chaos)
        sock.settimeout(None if self._timeout is None
                        else max(self._timeout, self.TRACKER_BARRIER_MIN_SEC))
        P.send_hello(sock, cmd, self._task_id, self._world_hint,
                     job=self._job_id)
        return sock

    def _rendezvous(self, cmd: str) -> None:
        """Register with the tracker, receive topology, wire up links."""
        self._close_links()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(64)
        my_port = self._listener.getsockname()[1]
        my_host = self._advertised_host()

        topo = self._register(cmd, my_host, my_port)

        self._rank = topo.rank
        self._world = topo.world
        self._epoch = topo.epoch
        self._relaunched = self._relaunched or bool(topo.relaunched)
        self._parent = topo.parent
        self._tree_links = list(topo.neighbors)
        self._ring_prev = topo.ring_prev
        self._ring_next = topo.ring_next
        # Host-group handout for the topology-aware schedules (one id
        # per rank; empty from a pre-sched tracker).
        self._groups = list(topo.groups)
        # Live adaptation handout: the controller's schedule directive
        # and demotion set (empty from a pre-adaptive tracker).
        demoted = frozenset(int(r) for r in topo.demoted)
        live = sched_mod.decode_directive(topo.sched)
        if live != self._sched_live or demoted != self._demoted:
            self._log.info("adaptive handout: sched=%r demoted=%s",
                           topo.sched, sorted(demoted))
            if self._obs_on:
                self._trace.emit("sched_directive", rank=self._rank,
                                 directive=topo.sched or None,
                                 demoted=sorted(demoted),
                                 epoch=self._epoch)
        self._sched_live = live
        self._demoted = demoted
        os.environ["RABIT_TPU_LOG_TAG"] = f"rank{self._rank}"
        # The link factory negotiates per-peer transports from the same
        # handout every rank received (host groups name the same-host
        # shm candidates), so both ends of every link agree; the label
        # keys auto-tuner lookups so shm and tcp measurements never
        # answer for each other.
        self._lf.set_topology(self._rank, self._groups)
        self._transport_label = self._lf.cfg.mode_label(self._groups)
        self._reconnect_links(topo)
        self._obs_transport = self._live_transport_label()

    def _register(self, cmd: str, my_host: str,
                  my_port: int) -> P.TopologyReply:
        """One rendezvous registration with the tracker, retried whole.

        The single dial already carries the connect retry/backoff
        schedule; this loop additionally survives the tracker dying
        UNDER the exchange — mid-handshake, or while this worker sat
        parked in the barrier (the reply recv fails when the
        coordinator's sockets vanish).  A supervisor restarting the
        tracker on the same port (journal replayed) therefore costs the
        workers one backoff walk, not the job.  Exhausting the budget
        raises :class:`TrackerLostError` (a LinkError: the robust
        recover loop treats it like any dead link and gives it the
        recover-attempt budget on top).

        A typed ADMISSION reject (multi-tenant tracker at --max-jobs /
        --max-total-workers capacity) rides its own, separate budget
        (``rabit_admission_retries``): the tracker re-admits the moment
        a finishing job frees the slot, so each backoff walk re-polls
        admission rather than giving up — and an exhausted budget
        raises typed :class:`AdmissionError`, never a hang."""
        attempts = max(self._connect_retries + 1, 1)
        adm_attempts = max(self._admission_retries + 1, 1)
        last: Optional[OSError] = None
        net_tries = 0
        adm_tries = 0
        shard_tries = 0
        while True:
            sock = None
            reply: P.TopologyReply | P.RejectReply | None = None
            try:
                sock = self._tracker_connect(cmd)
                if self._chaos is not None:
                    # Control-plane chaos (hello site): an injected
                    # reset tears the registration exchange exactly
                    # where a dying shard would — detected below as a
                    # net.tracker.register_retries walk (the pairing
                    # the chaos gates assert).
                    kind = self._chaos.link(chaos_mod.SITE_HELLO)
                    if kind == chaos_mod.KIND_RESET:
                        raise ConnectionResetError(
                            "[chaos] injected hello reset")
                P.send_str(sock, my_host)
                P.send_u32(sock, my_port)
                reply = P.TopologyReply.recv_or_reject(sock)
            except OSError as e:
                last = e
                net_tries += 1
                if self._obs_on:
                    self._metrics.counter("net.tracker.register_retries"
                                          ).inc()
                if net_tries >= attempts:
                    raise TrackerLostError(
                        f"tracker {self._tracker_addr[0]}:"
                        f"{self._tracker_addr[1]} unreachable: "
                        f"registration (cmd={cmd}) failed "
                        f"{net_tries} time(s): {last}") from last
                self._log.info("tracker registration (cmd=%s) failed "
                               "(%s); re-registering (attempt %d/%d)",
                               cmd, e, net_tries + 1, attempts)
                self._backoff(chaos_mod.SITE_TRACKER, net_tries, e)
                continue
            finally:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            if isinstance(reply, P.RejectReply) \
                    and reply.code == P.REJECT_SHARD_MOVED:
                # Sharded control plane: the job hashes to another
                # shard.  The reason carries the owner's generation and
                # endpoint — re-target without a directory round trip;
                # an old-format reason falls back to a full refresh.
                shard_tries += 1
                if self._obs_on:
                    self._metrics.counter("net.tracker.shard_redirects"
                                          ).inc()
                parsed = P.parse_shard_moved(reply.reason)
                if shard_tries > max(self._shard_retries, 0):
                    last_ep = (f"{parsed[2]}:{parsed[3]}" if parsed
                               else f"{self._tracker_addr[0]}:"
                                    f"{self._tracker_addr[1]}")
                    last_gen = parsed[0] if parsed else -1
                    raise ShardMovedError(
                        f"job {self._job_id!r} redirected "
                        f"{shard_tries} time(s) without landing on its "
                        f"owning shard (cmd={cmd}; last redirect: "
                        f"generation {last_gen}, endpoint {last_ep}): "
                        f"{reply.reason}",
                        generation=last_gen,
                        shard=parsed[1] if parsed else -1,
                        endpoint=last_ep)
                if parsed is not None:
                    gen, owner, host, port = parsed
                    self._log.info(
                        "tracker redirect: job %r owned by shard %d at "
                        "%s:%d (generation %d)", self._job_id, owner,
                        host, port, gen)
                    self._tracker_addr = (host, port)
                    if self._directory is not None:
                        self._directory.invalidate(gen)
                    if shard_tries >= 2:
                        # A second redirect in one walk means the
                        # membership is mid-flip (migration landing,
                        # leader failover): exponential full-jitter
                        # backoff so a world of redirected workers
                        # converges decorrelated instead of hammering
                        # each hop of a moving target in lockstep.
                        self._backoff(chaos_mod.SITE_TRACKER,
                                      shard_tries - 1, None)
                elif not self._redirect_tracker():
                    # No redirect payload and no directory to consult:
                    # back off and re-ask the same endpoint (its view
                    # may settle).
                    self._backoff(chaos_mod.SITE_TRACKER, shard_tries,
                                  None)
                continue
            if isinstance(reply, P.RejectReply):
                adm_tries += 1
                if self._obs_on:
                    self._metrics.counter("net.tracker.admission_rejects"
                                          ).inc()
                if reply.code == P.REJECT_BAD_HANDSHAKE:
                    # Not a capacity race: the tracker could not parse
                    # us (version/config skew) — retrying can't help.
                    raise AdmissionError(
                        f"tracker rejected the registration handshake "
                        f"(cmd={cmd}, job={self._job_id!r}): "
                        f"{reply.reason}",
                        code=reply.code, reason=reply.reason)
                if adm_tries >= adm_attempts:
                    raise AdmissionError(
                        f"job {self._job_id!r} refused admission "
                        f"{adm_tries} time(s) (cmd={cmd}): "
                        f"{reply.reason}",
                        code=reply.code, reason=reply.reason)
                self._log.info(
                    "tracker admission refused job %r (%s); backing off "
                    "and re-polling (attempt %d/%d)", self._job_id,
                    reply.reason, adm_tries + 1, adm_attempts)
                self._backoff(chaos_mod.SITE_TRACKER, adm_tries, None)
                continue
            return reply

    def _wrap_link(self, s: socket.socket, peer_rank: int):
        """Chaos interposition for an established link (after the
        handshake — connect-stage faults have their own sites)."""
        if self._chaos is None:
            return s
        return chaos_mod.ChaosSocket(s, self._chaos, peer_rank)

    def _reconnect_links(self, topo) -> None:
        """Wire the worker-worker links for a fresh topology.

        Outgoing dials (to lower ranks, already listening) honor
        rabit_timeout_sec AND the connect retry/backoff policy — during
        a rendezvous a peer is routinely slow to reach listen(), and
        one refused SYN must not kill the worker (native analogue:
        ConnectRetry, native/src/socket.cc).  Incoming accepts are
        bounded like the dials: a peer that died between its tracker
        reply and dialing us must surface as a timeout (-> rendezvous
        retry / fail-fast), not an unbounded accept() wedge.

        Each established socket is handed to the transport factory,
        which runs the link handshake (classic bytes under default
        config), negotiates shm/integrity features where configured,
        and applies the shared socket setup (rabit_sock_buf,
        TCP_NODELAY, timeout) on EVERY TCP link creation path — first
        wiring, recovery re-dials and shm→tcp failover alike.  This is
        the seam the live failover rides: a peer in the factory's
        denied set (its shm link failed mid-job) renegotiates here as
        plain TCP.
        """
        for peer_rank, host, port in topo.connect:
            s = self._dial_retry((host, port), chaos_mod.SITE_CONNECT)
            self._links[peer_rank] = self._lf.dial(s, peer_rank)
        self._listener.settimeout(self._timeout)
        for _ in range(topo.naccept):
            if self._chaos is not None:
                self._chaos.connect(chaos_mod.SITE_ACCEPT)
            s, _addr = self._listener.accept()
            link, peer_rank = self._lf.accept(s)
            self._links[peer_rank] = link
        self._listener.close()
        self._listener = None

    def _advertised_host(self) -> str:
        # Single-host jobs (tests, local launcher) rendezvous via loopback;
        # multi-host workers advertise the interface that routes to the
        # tracker.
        from rabit_tpu.utils.net import routable_ip

        return routable_ip(self._tracker_addr)

    def _close_links(self) -> None:
        for s in self._links.values():
            try:
                s.close()
            except OSError:
                pass
        self._links.clear()
        if self._listener is not None:
            self._listener.close()
            self._listener = None

    # ------------------------------------------------------------------
    # heartbeat liveness channel
    # ------------------------------------------------------------------
    def _start_heartbeat(self) -> None:
        """One persistent CMD_HEARTBEAT connection, fed by a daemon
        thread: the tracker's deadline sweep turns missing beats into a
        dead verdict (and a supervisor kill) without any collective op
        having to touch the hung rank first.  A SIGSTOP'd process stops
        this thread with everything else — which is exactly the
        signal.

        The **live telemetry plane** rides the same connection: with
        telemetry streaming armed (``rabit_obs`` + a non-zero
        ``rabit_obs_flush_sec``) the thread also ships one obs frame
        (delta metrics + buffered spans) per flush period — and opens
        the channel even when heartbeats proper are off, with the flush
        period as the advertised beat period, since frames prove
        liveness exactly like beats."""
        streaming = (self._obs_on and self._obs_flush_sec > 0
                     and self._world > 1)
        if (self._hb_sec <= 0 and not streaming) \
                or self._tracker_addr is None:
            return
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name="rabit-heartbeat", daemon=True)
        self._hb_thread.start()

    def _hb_period(self) -> float:
        return self._hb_sec if self._hb_sec > 0 else self._obs_flush_sec

    def _hb_dial(self) -> socket.socket:
        sock = self._tracker_connect(P.CMD_HEARTBEAT, chaos=False)
        P.send_u32(sock, max(int(self._hb_period() * 1000), 1))
        return sock

    def _hb_loop(self) -> None:
        sock: Optional[socket.socket] = None
        beat = 0
        sent: dict[int, float] = {}   # beat -> send time (rtt pairing)
        rbuf = bytearray()            # echo bytes straddling reads
        hb = self._hb_period()
        flush = (self._obs_flush_sec
                 if self._obs_on and self._obs_flush_sec > 0 else 0.0)
        now = time.monotonic()
        next_beat = now                # beat immediately at startup
        next_flush = now + flush if flush else None
        drops_row = 0                  # consecutive failed periods
        while True:
            now = time.monotonic()
            due = next_beat if next_flush is None \
                else min(next_beat, next_flush)
            if self._hb_stop.wait(max(due - now, 0.0)):
                break
            now = time.monotonic()
            try:
                if sock is None:
                    sock = self._hb_dial()
                    rbuf.clear()
                    sent.clear()
                    drops_row = 0
                    if self._obs_on:
                        self._metrics.counter("hb.connects").inc()
                if self._chaos is not None:
                    # Control-plane chaos (hb site): consult once per
                    # wake.  An injected reset drops the channel into
                    # the OSError path below (counted as hb.drops — the
                    # detection half of the pairing gate); the re-dial
                    # next period is the recovery under test.  Per-rule
                    # counters keep the other sites' schedules intact.
                    kind = self._chaos.link(chaos_mod.SITE_HB)
                    if kind == chaos_mod.KIND_RESET:
                        raise ConnectionResetError(
                            "[chaos] injected heartbeat reset")
                if now >= next_beat:
                    beat += 1
                    if flush:
                        sent[beat] = time.perf_counter()
                        while len(sent) > 64:  # bound: unechoed beats
                            sent.pop(min(sent))
                    P.send_u32(sock, beat)
                    if self._obs_on:
                        self._metrics.counter("hb.sent").inc()
                    next_beat = now + hb
                if next_flush is not None and now >= next_flush:
                    self._obs_send_frame(sock)
                    next_flush = now + flush
                if flush:
                    # Wait briefly for the just-sent beat's echo: an
                    # rtt sample recorded only at the NEXT wake would
                    # measure the loop period, not the round trip.
                    self._hb_drain_echoes(sock, sent, rbuf,
                                          wait_sec=min(0.25, hb / 4))
                    # Beats a non-echoing tracker (pre-obs) never
                    # answers must not pin the wait branch on forever:
                    # expire them after a few periods.
                    cut = time.perf_counter() - 4 * hb
                    for b in [b for b, t in sent.items() if t < cut]:
                        del sent[b]
            except OSError as e:
                # Tracker unreachable (restarting, mid-teardown): drop
                # the channel and re-dial next period — liveness is
                # best effort, never a reason to kill a healthy worker.
                # Pacing: push every deadline one period out so a dead
                # tracker never turns this loop into a re-dial spin.
                self._log.debug("heartbeat send/dial failed: %s", e)
                if self._obs_on:
                    self._metrics.counter("hb.drops").inc()
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                drops_row += 1
                if drops_row >= 2:
                    # Two consecutive failed periods is a DEAD endpoint,
                    # not a restart blip: re-resolve the job's owner so
                    # a migrated/failed-over job's liveness channel
                    # follows it (one injected chaos reset can never
                    # reach here — a reset only fires on an open
                    # channel, i.e. right after a successful dial
                    # zeroed the run, so the seeded schedules and the
                    # injected↔detected pairing stay intact).
                    if self._redirect_tracker():
                        drops_row = 0
                now = time.monotonic()
                next_beat = now + hb
                if next_flush is not None:
                    next_flush = now + flush
        if sock is not None:
            try:
                if flush:
                    self._obs_send_frame(sock)  # final deltas + spans
                P.send_u32(sock, P.HEARTBEAT_BYE)  # clean shutdown
                sock.close()
            except OSError:
                pass

    def _obs_send_frame(self, sock: socket.socket) -> None:
        """Ship one delta frame + the buffered spans (wire format:
        protocol.HEARTBEAT_OBS, u32 length, JSON)."""
        obs.note_drops(self._metrics, self._trace)
        payload = {"rank": self._rank, "world": self._world,
                   "engine": type(self).__name__, "epoch": self._epoch,
                   # The wire the measurements RODE (not just the one
                   # configured): the controller's online TuningCache
                   # merges key on it, so schedule verdicts learned
                   # over shm never answer a tcp job — and a rank whose
                   # shm lanes fell over (or fell back) to tcp stops
                   # filing tcp-measured verdicts under allreduce@shm.
                   "transport": self._obs_transport,
                   # The wire codec (replicated config): keys the
                   # controller's online TuningCache merges like the
                   # transport, so schedule verdicts measured over a
                   # quantized wire never answer a full-width job.
                   "codec": self._codec_label,
                   # Which implementation runs the codec hop math
                   # (native / numpy / numpy-fallback): purely
                   # informational — bit-identical either way — but a
                   # silent fallback to numpy is a silent perf cliff,
                   # so /status and rabit_top surface it per rank.
                   "codec_impl": self._codec_impl,
                   # Send-side wall clock: with the hb-RTT estimate the
                   # tracker turns (arrival - ts - rtt/2) into a clock-
                   # offset sample, so assembled hop timelines survive
                   # cross-host clock skew (TraceAssembler.note_offset).
                   "ts": round(time.time(), 6)}
        payload.update(self._exporter.frame())
        spans = self._span_buf.drain()
        if spans:
            payload["spans"] = spans
        if self._span_buf.dropped:
            payload["spans_dropped"] = self._span_buf.dropped
        if self._hop_buf is not None:
            hops = self._hop_buf.drain()
            if hops:
                payload["hops"] = hops
            if self._hop_buf.dropped:
                payload["hops_dropped"] = self._hop_buf.dropped
        raw = json.dumps(payload).encode()
        # Pad to a u32 boundary (JSON tolerates trailing whitespace):
        # every frame then occupies whole 4-byte words, so a reader
        # that treats the stream as plain u32 beats — a pre-obs
        # tracker — stays ALIGNED and still recognizes the final
        # HEARTBEAT_BYE (no payload word can collide: ASCII JSON and
        # 0x20 padding never form 0xFFFFFFFF).
        raw += b" " * (-len(raw) % 4)
        sock.sendall(struct.pack("<II", P.HEARTBEAT_OBS, len(raw)) + raw)
        self._metrics.counter("obs.frames").inc()

    def _hb_drain_echoes(self, sock: socket.socket, sent: dict[int, float],
                         rbuf: bytearray,
                         wait_sec: float = 0.0) -> None:
        """Consume whatever beat echoes the tracker sent back and fold
        them into the ``hb.rtt.seconds`` histogram.  ``wait_sec``
        bounds how long to wait for the first echo (rtt is measured at
        READ time, so the wait right after a beat keeps the sample an
        actual round trip instead of a loop period); once nothing is
        outstanding or the budget is spent, reads go non-blocking.  A
        tracker that never echoes (pre-obs version) just yields no
        samples."""
        deadline = time.monotonic() + wait_sec
        while True:
            left = deadline - time.monotonic()
            if not sent:
                left = 0.0
            readable, _, _ = select.select([sock], [], [], max(left, 0.0))
            if not readable:
                return
            data = sock.recv(4096)
            if not data:
                raise ConnectionResetError("tracker closed the "
                                           "heartbeat channel")
            rbuf += data
            now = time.perf_counter()
            while len(rbuf) >= 4:
                (echo,) = struct.unpack_from("<I", rbuf)
                del rbuf[:4]
                t0 = sent.pop(echo, None)
                if t0 is not None:
                    self._metrics.histogram("hb.rtt.seconds").observe(
                        now - t0)

    def _stop_heartbeat(self) -> None:
        t = self._hb_thread
        if t is None:
            return
        self._hb_stop.set()
        t.join(timeout=5)
        self._hb_thread = None

    def shutdown(self) -> None:
        self._fence()
        self._stop_pump()
        self._stop_heartbeat()
        self._obs_flush()
        if self._tracker_addr is not None:
            try:
                sock = self._tracker_connect(P.CMD_SHUTDOWN)
                sock.close()
            except OSError as e:
                self._log.debug("shutdown notify failed (tracker gone?): %s",
                                e)
        self._close_links()

    # ------------------------------------------------------------------
    # telemetry (rabit_tpu.obs)
    # ------------------------------------------------------------------
    def stats(self) -> dict:
        if not self._obs_on or self._metrics is None:
            return {}  # disabled telemetry reports nothing (interface.py)
        return self._metrics.snapshot()

    def events(self) -> list[dict]:
        return self._trace.events() if self._trace is not None else []

    def _op_seqno(self) -> Optional[int]:
        """Robust-protocol seqno for op events (None on the base engine,
        which has no op numbering)."""
        return None

    def _op_done(self, kind: str, nbytes: int, t0: float,
                 replayed: bool = False) -> None:
        """Record one completed collective (call sites gate on _obs_on)."""
        dt = time.perf_counter() - t0
        obs.record_op(self._metrics, self._trace, kind, nbytes, dt,
                      self._rank, seqno=self._op_seqno(),
                      version=self._version, replayed=replayed)
        if self._span_buf is not None and not replayed:
            # Cross-rank span for the live plane: keyed (epoch,
            # version, seq, kind) so the tracker can merge the same op
            # across ranks.  The protocol seqno is the shared
            # coordinate on pyrobust; the base engine's op stream is
            # lockstep program order, so a per-engine running index
            # aligns the same way.  REPLAYED ops ship no span — a
            # relaunched rank re-serving (version, seq) minutes after
            # the survivors executed it would otherwise merge into
            # their group as a giant bogus lateness.
            seq = self._op_seqno()
            if seq is None:
                seq = self._span_seq
                self._span_seq += 1
            end = time.time()
            self._span_buf.add(
                seq, self._epoch, self._version, kind,
                self._op_sched if kind.startswith("allreduce") else None,
                nbytes, end - dt, end,
                # Per-op EFFECTIVE wire format: the tracker scopes the
                # controller's schedule evidence (and hence the tuner
                # merges) to the job's codec wire — an opted-out or
                # ineligible op's full-width measurement never answers
                # codec-keyed rows (span.py sched_costs).
                wire=(self._op_wire if kind.startswith("allreduce")
                      else "none"))

    def _obs_flush(self) -> None:
        """Ship the rank-local summary to the tracker's obs channel and
        dump the event trace under rabit_obs_dir (both best-effort; runs
        once, at the head of shutdown)."""
        if not self._obs_on:
            return
        obs.note_drops(self._metrics, self._trace)
        if self._tracker_addr is not None and self._world > 1:
            obs.ship_summary(
                self.tracker_print, self._log, type(self).__name__,
                self._rank, self._world, self._metrics.snapshot(),
                [e for e in self._trace.events()
                 if e.get("name") not in ("op", "sched")],
                job=self._job_id)
        if self._obs_dir:
            obs.dump_events(self._log, self._obs_dir, self._rank,
                            self._trace.events())

    # ------------------------------------------------------------------
    # flight recorder (doc/observability.md "Causal tracing & postmortem")
    # ------------------------------------------------------------------
    def flight_persist(self, reason: str, **fields) -> Optional[str]:
        """Persist this rank's flight record (atomic, best effort;
        no-op without ``rabit_trace_dir``).  Public: the serving plane
        calls it on drain, supervisors may call it before teardown."""
        if self._flight is None or not self._trace_dir:
            return None
        return self._flight.persist(
            self._trace_dir, self._rank, reason, job=self._job_id,
            world=self._world, epoch=self._epoch,
            engine=type(self).__name__, **fields)

    def _install_flight_sigterm(self) -> None:
        """Chain a flight-record persist in front of whatever SIGTERM
        behaviour the process already has — a supervisor's kill then
        leaves forensics behind.  Only possible from the main thread
        (signal module rule); engines constructed elsewhere simply keep
        the LinkError/recovery persist paths."""
        if not self._trace_dir:
            return
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                self.flight_persist("sigterm")
                if callable(prev):
                    prev(signum, frame)
                else:
                    # Restore the default disposition and re-raise so
                    # the exit status still says "killed by SIGTERM".
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            # Not the main thread of the main interpreter.
            self._log.debug("flight recorder: SIGTERM hook unavailable "
                            "off the main thread")

    # ------------------------------------------------------------------
    # identity
    # ------------------------------------------------------------------
    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world

    @property
    def was_relaunched(self) -> bool:
        return self._relaunched

    @property
    def epoch(self) -> int:
        """Membership epoch of the topology this engine runs under
        (bumped by the tracker per completed elastic rescale round)."""
        return self._epoch

    # One SHORT dial per commit-boundary epoch poll: the poll is
    # best-effort by contract, so it must never inherit the rendezvous
    # dial's retry schedule (up to rabit_timeout_sec — default 600 s —
    # against a SYN-dropping partitioned tracker, at EVERY commit on
    # EVERY rank).
    EPOCH_POLL_TIMEOUT_SEC = 2.0

    def _tracker_epoch_poll(self) -> Optional[tuple[int, int, int]]:
        """One-shot ``cmd=epoch`` membership poll: reports this rank's
        committed version, returns ``(epoch, target_epoch,
        target_world)`` — or None when the tracker is unreachable,
        which callers must read as "no change" (an elastic job keeps
        training through a coordinator outage; only rendezvous truly
        needs the tracker).  Dials raw with a short timeout and no
        retries — a restarting tracker costs a commit at most
        EPOCH_POLL_TIMEOUT_SEC, never the connect budget.  Chaos-exempt
        like the heartbeat channel: polls interleave with the op stream
        nondeterministically, so letting them consume the plan would
        break seed replay."""
        try:
            sock = socket.create_connection(
                self._tracker_addr, timeout=self.EPOCH_POLL_TIMEOUT_SEC)
        except OSError:
            return None
        try:
            sock.settimeout(self.EPOCH_POLL_TIMEOUT_SEC)
            P.send_hello(sock, P.CMD_EPOCH, self._task_id,
                         self._world_hint, job=self._job_id)
            P.send_u32(sock, self._version & 0xFFFFFFFF)
            return (P.recv_u32(sock), P.recv_u32(sock), P.recv_u32(sock))
        except OSError as e:
            self._log.debug("epoch poll failed (tracker restarting?): %s",
                            e)
            return None
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def tracker_print(self, msg: str) -> None:
        # One-shot command connect, best effort by design: a tracker
        # that died after the last barrier must never turn a worker's
        # successful exit into a traceback — the message falls back to
        # the local stream instead (interface.py's default behaviour).
        try:
            sock = self._tracker_connect(P.CMD_PRINT)
            P.send_str(sock, msg)
            sock.close()
        except OSError as e:
            self._log.debug("tracker print failed (tracker gone?): %s", e)
            if not msg.startswith(obs.OBS_SUMMARY_PREFIX):
                print(f"@tracker[{self._rank}] {msg}", flush=True)

    # ------------------------------------------------------------------
    # link IO helpers (delegating to rabit_tpu/transport)
    # ------------------------------------------------------------------
    def _live_transport_label(self) -> str:
        """The wire label streamed with obs frames: the replicated
        ``mode_label`` (which keys DISPATCH tuner picks and must stay a
        collective decision), degraded to the truth this rank can see.
        A rank that was nominated same-host peers yet holds no live shm
        link — universal fallback (unwritable shm dir, attach refusals)
        or mid-job failover denial — reports ``tcp``, so the
        controller's online TuningCache merges never file tcp-measured
        verdicts under the ``@shm`` rows.  A rank with no same-group
        link peer defers to the world label: its measurements ride the
        same collectives as the shm-paired ranks'."""
        if self._transport_label != "shm":
            return self._transport_label
        if any(lk.kind == "shm" for lk in self._links.values()):
            return "shm"
        if any(self._lf.same_group(peer) for peer in self._links):
            return "tcp"
        return "shm"

    def _note_link_error(self, exc: LinkError) -> None:
        """Failure attribution for the LIVE FAILOVER path: a LinkError
        raised inside a shm link (health probe, ring fault, integrity
        escalation) marks that peer transport-denied, so the recover
        rendezvous this same exception is about to trigger re-dials the
        link as plain TCP — mid-job, visible in the
        ``transport.failover.*`` counters and the tracker timeline,
        never a hang.  TCP failures change nothing here (there is no
        transport below TCP to fall to; recovery handles them as
        always).

        Every LinkError — any transport — additionally lands in the
        flight recorder and (with ``rabit_trace_dir`` set) persists it:
        a surviving rank's record names the peer it was blocked on at
        the moment the world broke, which is exactly the evidence
        ``tools/postmortem.py`` votes the first-dead rank from."""
        link = getattr(exc, "link", None)
        peer = getattr(link, "peer", None)
        if self._flight is not None:
            self._flight.note("link_error", rank=self._rank, peer=peer,
                              error=type(exc).__name__,
                              detail=str(exc)[:160])
            self.flight_persist("link_error", peer=peer)
        if link is None or link.kind != "shm":
            return
        if not self._lf.deny(link.peer):
            return
        self._log.warn("transport: shm link to rank %d failed (%s: %s); "
                       "failing over to tcp at the next rendezvous",
                       link.peer, type(exc).__name__, exc)
        if self._obs_on:
            self._metrics.counter("transport.failover").inc()
            self._metrics.counter("transport.failover.shm_to_tcp").inc()
            self._trace.emit("transport", phase="failover",
                             rank=self._rank, peer=link.peer,
                             error=type(exc).__name__)

    def _send(self, rank: int, data: bytes | memoryview) -> None:
        try:
            self._links[rank].sendall(data)
        except LinkError as e:
            self._note_link_error(e)
            raise

    def _recv(self, rank: int, nbytes: int, into: memoryview | None = None):
        try:
            return self._links[rank].recv_exact(nbytes, into)
        except LinkError as e:
            self._note_link_error(e)
            raise

    def _sendv(self, rank: int, *parts) -> None:
        """Scatter-gather send: coalesce several buffers (header +
        payload, fused-op member blocks) into as few syscalls as the
        transport allows — the byte stream is identical to sequential
        ``sendall`` calls."""
        try:
            self._links[rank].sendv(parts)
        except LinkError as e:
            self._note_link_error(e)
            raise

    def _recv_all(self, ranks: list[int], nbytes: int,
                  bufs: list[memoryview]) -> None:
        """Multi-link pump: fill ``bufs[i][:nbytes]`` from ``ranks[i]``,
        draining every link concurrently (bytes are consumed in arrival
        order across links, so one slow child no longer serializes its
        siblings).  Callers merge in deterministic rank order afterwards
        — reduction order is unchanged."""
        try:
            tr.recv_all([self._links[r] for r in ranks], nbytes, bufs,
                        self._timeout)
        except LinkError as e:
            self._note_link_error(e)
            raise

    def _exchange(self, send_rank: int, send_data: memoryview,
                  recv_rank: int, recv_buf: memoryview) -> None:
        """Full-duplex: stream send_data to one peer while filling
        recv_buf from another — avoids ring deadlock without threads."""
        self._exchange_v(send_rank, [send_data], recv_rank, [recv_buf])

    def _exchange_v(self, send_rank: int, send_parts: list,
                    recv_rank: int, recv_parts: list) -> None:
        """Vectored full-duplex exchange: scatter-gather send of
        ``send_parts`` (no intermediate concatenation copy) while
        filling ``recv_parts`` in order.  The fused segmented-ring hot
        path moves every member's block through here."""
        try:
            tr.exchange(self._links[send_rank], send_parts,
                        self._links[recv_rank], recv_parts,
                        self._timeout)
        except LinkError as e:
            self._note_link_error(e)
            raise

    # ------------------------------------------------------------------
    # hop pipelining (doc/performance.md "Hop pipelining")
    # ------------------------------------------------------------------
    def _hop_exchange_merge(self, send_rank: int, sblk, recv_rank: int,
                            rbytes: int, cbytes: int, item: int,
                            merge, what: str = "hop") -> None:
        """One collective hop: stream ``sblk`` to ``send_rank`` while
        receiving ``rbytes`` from ``recv_rank`` in chunks, folding each
        received chunk via ``merge(coff, rl, src)`` (``rl`` bytes at
        hop byte-offset ``coff``).  This is the schedules' pipelined
        exchange+merge primitive: with ``rabit_pipeline_depth`` > 1 and
        a hop large enough to split, up to depth chunk exchanges stay
        in flight while earlier chunks merge — the NIC no longer idles
        during ``_wire_merge`` (or the codec's dequant/requant) and the
        CPU no longer idles during the wire.  Depth 1 (or a hop that
        fits one pipeline chunk) runs the legacy serial loop.  Results
        are bit-identical across depths: merges touch disjoint
        item-aligned ranges in the same order with the same values, and
        the per-link byte stream is depth-independent — mixed-depth
        peers interoperate.

        ``cbytes`` is the caller's reduce-buffer chunk budget; the
        pipeline sub-chunk is ``cbytes // depth`` floored at
        ``rabit_pipeline_chunk`` (item-aligned) — each chunk boundary
        is a sync point, so tiny chunks are never worth it — and the
        in-flight window is capped so its leases together never exceed
        the single-chunk budget: ``rabit_reduce_buffer`` stays an
        honest per-op scratch ceiling with the pipeline armed
        (``_note_scratch`` covers every lease).  Either side may be
        empty (the halving fold pre-step pipelines a recv-only drain).
        Ragged tails and zero-length sides take the same clamped
        sub-steps on both ends of every link."""
        slen = len(sblk)
        # Sampled-op tracing: one "hop" record per call (the op-local
        # hop index and the egress peer key the cross-rank timeline),
        # emitted on SUCCESS only — a hop that died leaves its evidence
        # in the flight recorder instead.
        traced = self._op_traced
        t_hop = time.perf_counter() if traced else 0.0
        depth = self._pipe_depth
        if depth > 1 and (slen or rbytes):
            pcb = min(cbytes, max(cbytes // depth, self._pipe_chunk))
            pcb = max(pcb - pcb % item, item)
            nsteps = max(-(-slen // pcb), -(-rbytes // pcb))
            # Window cap: the in-flight leases (window * pcb) must fit
            # the CONFIGURED budget — cbytes may be block-capped well
            # below it, and a floor-raised pcb may not divide it.
            window = min(depth, nsteps,
                         max(self._reduce_buffer // pcb, 1))
            if nsteps >= 2 and window >= 2:
                self._hop_pipelined(send_rank, sblk, recv_rank, rbytes,
                                    pcb, merge, nsteps, window, what)
                if traced:
                    self._trace_hop("hop", send_rank, max(slen, rbytes),
                                    time.perf_counter() - t_hop)
                return
        # Legacy serial hop loop (depth 1, or nothing to overlap):
        # exchange one chunk, merge it, repeat — byte-identical to the
        # pre-pipeline engine.
        nsteps = max(-(-slen // cbytes), -(-rbytes // cbytes), 0)
        if not nsteps:
            return
        lease = self._arena.take(min(cbytes, max(rbytes, 1)))
        self._note_scratch(len(lease))
        try:
            for ci in range(nsteps):
                coff = ci * cbytes
                sl = min(cbytes, max(slen - coff, 0))
                rl = min(cbytes, max(rbytes - coff, 0))
                self._exchange(send_rank, sblk[coff:coff + sl],
                               recv_rank, lease[:rl])
                if rl:
                    merge(coff, rl, lease[:rl])
        finally:
            self._arena.give(lease)
        if traced:
            self._trace_hop("hop", send_rank, max(slen, rbytes),
                            time.perf_counter() - t_hop)

    def _pipe_run(self, send_rank: int, recv_rank: int, what: str,
                  body) -> None:
        """Run ``body(pipe)`` under the choreography every pipelined
        hop shares: open (pump_begin may raise on a dead link), flush
        + restore on success, ABORT on any exception (framed backlog
        dropped — recovery rewires the links from scratch), and
        LinkError attribution through :meth:`_note_link_error` so a
        failing shm link still earns its tcp failover.  One copy of
        the discipline, used by :meth:`_hop_pipelined` and the fused
        segmented ring."""
        pipe = None
        try:
            try:
                pipe = tr.HopPipeline(self._links[send_rank],
                                      self._links[recv_rank],
                                      self._timeout, what)
                body(pipe)
                pipe.close()
            except BaseException:
                if pipe is not None:
                    pipe.abort()
                raise
        except LinkError as e:
            self._note_link_error(e)
            raise

    def _hop_pipelined(self, send_rank: int, sblk, recv_rank: int,
                       rbytes: int, pcb: int, merge, nsteps: int,
                       window: int, what: str) -> None:
        """The depth-window body of :meth:`_hop_exchange_merge`: chunk
        k merges while chunk k+1's exchange is in flight on the
        transport pump.  Scratch: one recv lease per window slot —
        chunk ci reuses lease ``ci % window``, safe because ci only
        pushes after ci-window (the slot's previous user) was popped
        and merged."""
        depth = window
        slen = len(sblk)
        lease_bytes = min(pcb, max(rbytes, 1))
        leases = [self._arena.take(lease_bytes) for _ in range(depth)]
        self._note_scratch(lease_bytes * depth)
        track = self._obs_on
        traced = self._op_traced
        t_overlap = 0.0

        def body(pipe) -> None:
            nonlocal t_overlap

            def pop_merge() -> None:
                nonlocal t_overlap
                coff, rl, li = pipe.pop()
                if not rl:
                    return
                if (track and pipe.inflight) or traced:
                    t0 = time.perf_counter()
                    merge(coff, rl, leases[li][:rl])
                    dt = time.perf_counter() - t0
                    if track and pipe.inflight:
                        t_overlap += dt
                    if traced:
                        # Per-chunk record: one pipelined merge window
                        # (shares the enclosing hop's index — the hop
                        # record files after the pipe drains).
                        self._trace_hop("chunk", recv_rank, rl, dt)
                else:
                    merge(coff, rl, leases[li][:rl])

            for ci in range(nsteps):
                if ci >= depth:
                    pop_merge()
                coff = ci * pcb
                sl = min(pcb, max(slen - coff, 0))
                rl = min(pcb, max(rbytes - coff, 0))
                pipe.push([sblk[coff:coff + sl]] if sl else [],
                          [leases[ci % depth][:rl]] if rl else [],
                          (coff, rl, ci % depth))
            while pipe.inflight:
                pop_merge()

        try:
            self._pipe_run(send_rank, recv_rank, what, body)
        finally:
            for lease in leases:
                self._arena.give(lease)
        if track:
            m = self._metrics
            m.counter("pipe.ops").inc()
            m.counter("pipe.chunks").inc(nsteps)
            m.gauge("pipe.chunks_inflight").set(min(depth, nsteps))
            m.gauge("pipe.scratch_bytes").set(lease_bytes * depth)
            m.histogram("pipe.overlap.seconds").observe(t_overlap)

    # ------------------------------------------------------------------
    # collectives
    # ------------------------------------------------------------------
    def allreduce(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        codec: bool = True,
    ) -> np.ndarray:
        self._fence()
        return self._allreduce_blocking(buf, op, prepare_fun, codec)

    def _allreduce_blocking(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        codec: bool = True,
    ) -> np.ndarray:
        """The blocking op body, also run (in issue order) by the async
        progress thread — which must not re-enter the fence.
        ``codec=False`` is the per-op precision opt-out: this op rides
        the classic full-width wire even with a lossy codec armed
        (program order, hence deterministic across ranks — like
        ``fuse=False``)."""
        if prepare_fun is not None:
            prepare_fun()
        if self._world == 1:
            return buf
        if not self._obs_on:
            self._allreduce_impl(buf, op, codec)
            return buf
        t0 = time.perf_counter()
        self._allreduce_impl(buf, op, codec)
        self._op_done("allreduce", buf.nbytes, t0)
        return buf

    def _wire_eligible(self, dtype, op: ReduceOp, nbytes: int = 1) -> bool:
        """Does an ELEMENTWISE wire codec (bf16) apply?  One predicate
        for the cast itself and for fused-member classification — the
        two must never disagree on which algorithm a payload rides.
        Block-scaled codecs answer False here (their wire elements are
        whole blocks, not castable member views — fused buckets take
        the concatenate path instead)."""
        c = self._codec
        return (c is not None and c.elementwise
                and c.eligible(dtype, op, nbytes))

    def _wire_cast(self, buf: np.ndarray, op: ReduceOp):
        """When the bf16 wire format applies to this op, return the
        (transport_u16_array, reduce_dtype) pair; else None (see
        codec/base.py — the cast itself now lives on the codec)."""
        if not self._wire_eligible(buf.dtype, op, buf.nbytes):
            return None
        return self._codec.encode(buf)

    def _solo_wire_nbytes(self, dtype, op: ReduceOp, nbytes: int) -> int:
        """TRUE wire bytes a solo dispatch of this payload would move:
        the codec's honest ratio (codec.wire_nbytes) — never a
        hardcoded per-format special case — so schedule selection and
        the adaptive controller account real bytes for every codec."""
        c = self._codec
        if c is not None and c.eligible(dtype, op, nbytes):
            return c.wire_nbytes(nbytes)
        return nbytes

    def _op_codec_for(self, nbytes: int) -> Optional["codec_mod.Codec"]:
        """The codec THIS dispatch rides: the job codec
        (``rabit_wire_codec``), unless the adaptive controller's live
        directive names a per-op override for the op's payload bucket
        (the ``bytes:sched/codec`` entry form — doc/performance.md
        "Online adaptation").  The directive is part of the replicated
        topology handout and the block/floor config is uniform, so the
        override is a collective decision exactly like the job codec;
        instances are built once and cached.  An unknown codec name
        (version skew) keeps the job codec, loudly, once.  Like the
        directive's schedule half, the override never applies over an
        explicitly forced ``rabit_sched=<name>`` (forced modes are the
        operator's pin; the replicated mode string keeps the skip a
        collective decision too)."""
        if not self._sched_live or self._sched_name not in ("static",
                                                            "auto"):
            return self._codec
        name = sched_mod.directive_codec(self._sched_live, nbytes)
        if name is None or name == self._codec_label:
            return self._codec
        got = self._codec_byname.get(name, False)
        if got is False:
            if name in codec_mod.CODECS or name in codec_mod.ALIASES:
                got = codec_mod.make(name, self._codec_block,
                                     self._codec_min_bytes,
                                     kernel=self._codec_kernel)
            else:
                self._log.info(
                    "directive codec %r is not in this engine's "
                    "vocabulary; the bucket keeps the job codec (%s)",
                    name, self._codec_label)
                got = self._codec
            self._codec_byname[name] = got
        return got

    def _wire_merge(self, op: ReduceOp, rflat: np.ndarray, e0: int,
                    ne: int, src: np.ndarray,
                    record: bool = True) -> None:
        """The schedules' single reduction primitive: fold ``ne``
        received elements into ``rflat[e0:e0+ne]``.  Classic and
        elementwise-codec ops reduce with ``apply_op_numpy`` in the
        schedule's red dtype; under an armed block-scaled codec the
        elements ARE encoded blocks and the codec's
        dequantize→accumulate→requantize merge runs instead, recording
        the requantization residual at the matching positions (``e0``
        is the absolute element offset within the full wire array).
        ``record=False`` merges identically but skips the residual
        ledger — for schedules whose pairings run the same merge on
        BOTH sides (swing), where recording twice would double the
        error-feedback correction for one quantization event.

        Codec hop math (both impls) is timed into ``_op_ck_time`` so
        the obs plane can report per-op codec kernel seconds
        (``codec.kernel.seconds``) — the honest kernel-vs-numpy A/B
        coordinate.  Classic full-width merges stay untimed."""
        c = self._op_codec
        if c is None:
            k = self._op_elem_k
            if k is not None and ne:
                # Armed native bf16 elementwise merge: the same
                # upcast-add-RNE ml_dtypes performs, compiled.
                t0 = time.perf_counter()
                k.bf16_merge(ck_mod.pu16(rflat[e0:e0 + ne]),
                             ck_mod.pu16(src), ne)
                self._op_ck_time += time.perf_counter() - t0
                return
            if self._op_wire != "none":
                t0 = time.perf_counter()
                apply_op_numpy(op, rflat[e0:e0 + ne], src[:ne])
                self._op_ck_time += time.perf_counter() - t0
                return
            apply_op_numpy(op, rflat[e0:e0 + ne], src[:ne])
        else:
            t0 = time.perf_counter()
            c.merge(self._op_cstate, rflat, e0, ne, src, record)
            self._op_ck_time += time.perf_counter() - t0

    def _allreduce_impl(self, buf: np.ndarray, op: ReduceOp,
                        codec_ok: bool = True) -> None:
        """One allreduce through the wire, instrumented for forensics:
        the flight recorder learns what's in flight (kind, seqno,
        epoch, version — cleared only on success, so a fault-path
        persist names the op the world died in), and on a sampled op
        (``rabit_trace_sample``) the hop/chunk/codec-window trace
        records arm.  Both keys are deterministic in the op seqno
        (protocol seqno on pyrobust, the lockstep op index here), so
        every rank traces the SAME ops.  Shared with the robust layer's
        retry path — a replayed op re-arms, its wire work is real."""
        seq = self._op_seqno()
        if seq is None:
            seq = self._op_count
            self._op_count += 1
        fl = self._flight
        if fl is not None:
            fl.op_begin("allreduce", seq, self._epoch, self._version,
                        buf.nbytes)
        if self._hop_buf is not None \
                and obs.trace_sampled(seq, self._trace_sample):
            self._op_traced = True
            self._op_trace_key = (seq, self._epoch, self._version,
                                  "allreduce")
            self._hop_idx = 0
            try:
                self._allreduce_wire(buf, op, codec_ok)
            finally:
                self._op_traced = False
        else:
            self._allreduce_wire(buf, op, codec_ok)
        if fl is not None:
            fl.op_end()

    def _trace_hop(self, phase: str, peer: int, nbytes: int,
                   dt: float) -> None:
        """File one hop/chunk/codec-window record for the armed op
        (callers gate on ``_op_traced``).  Stamped like spans: wall
        clock at END minus the perf_counter-measured duration."""
        seq, epoch, version, kind = self._op_trace_key
        hop = self._hop_idx
        if phase == "hop":
            self._hop_idx = hop + 1
        end = time.time()
        self._hop_buf.add(seq, epoch, version, kind, hop, peer, phase,
                          nbytes, end - dt, end)

    def _allreduce_wire(self, buf: np.ndarray, op: ReduceOp,
                        codec_ok: bool = True) -> None:
        """Uninstrumented schedule dispatch (shared with the robust
        layer's retry path, which does its own accounting), wrapped in
        the wire-codec window when one applies.  ``codec_ok=False`` is
        the per-op precision escape hatch (api ``codec=False``).

        Block-scaled path: encode (carried residual added in) → the
        structured wire array rides ANY schedule (dispatch sees the
        true wire bytes) with merges routed through _wire_merge →
        decode + transactional feedback commit.  A LinkError escapes
        BEFORE the commit, so pyrobust's retry re-encodes identical
        bytes from the pristine buffer."""
        c = self._op_codec_for(buf.nbytes)
        if c is None or not codec_ok \
                or not c.eligible(buf.dtype, op, buf.nbytes):
            # Classic full-width wire — including per-op opt-outs and
            # ineligible ops in a codec-armed job, whose tuner picks
            # must answer from the full-width rows, never the codec's.
            self._op_wire = "none"
            self._allreduce_dispatch(buf, op, pick_codec="none")
            return
        self._op_wire = c.name  # span label: this op rode the codec
        traced = self._op_traced  # codec windows of a sampled op
        self._op_ck_time = 0.0  # per-op codec hop-math seconds
        if c.elementwise:
            t0 = time.perf_counter() if traced else 0.0
            w, red = c.encode(buf)
            if traced:
                self._trace_hop("encode", -1, buf.nbytes,
                                time.perf_counter() - t0)
            # Arm the compiled bf16 merge for this window only
            # (eligibility already pinned op == SUM): the schedules'
            # elementwise merges run the same upcast-add-RNE the
            # ml_dtypes path performs, bit for bit.
            if self._codec_kernel is not None and c.name == "bf16":
                self._op_elem_k = self._codec_kernel
            try:
                self._allreduce_dispatch(w, op, red,
                                         logical_nbytes=buf.nbytes,
                                         pick_codec=c.name)
            finally:
                self._op_elem_k = None
            t0 = time.perf_counter() if traced else 0.0
            buf.reshape(-1)[:] = c.decode(w, red)
            if traced:
                self._trace_hop("decode", -1, buf.nbytes,
                                time.perf_counter() - t0)
            self._note_codec_op(c, buf.nbytes, w.nbytes)
            return
        flat = buf.reshape(-1)
        t0 = time.perf_counter()
        state = c.begin(flat, self._feedback)
        dt = time.perf_counter() - t0
        self._op_ck_time += dt
        if traced:
            self._trace_hop("encode", -1, flat.nbytes, dt)
        self._op_codec, self._op_cstate = c, state
        try:
            self._allreduce_dispatch(state.wire, op,
                                     logical_nbytes=flat.nbytes,
                                     pick_codec=c.name)
        finally:
            self._op_codec, self._op_cstate = None, None
        t0 = time.perf_counter()
        res = c.finish(state, flat, self._feedback)
        dt = time.perf_counter() - t0
        self._op_ck_time += dt
        if traced:
            self._trace_hop("decode", -1, flat.nbytes, dt)
        self._note_codec_op(c, flat.nbytes, state.wire.nbytes, res)

    def _note_codec_op(self, c, logical: int, wire: int,
                       res: Optional[np.ndarray] = None) -> None:
        """Codec telemetry: bytes saved, compression ratio, the
        error-feedback norm and the per-op codec kernel time (hop math
        seconds, either implementation — the kernel-vs-numpy A/B
        coordinate), live-streamed like every other counter.  The
        ``codec.impl.native`` gauge makes a silent numpy fallback
        visible wherever metrics land (rabit_top, /status)."""
        if not self._obs_on:
            return
        m = self._metrics
        m.counter("codec.ops").inc()
        m.counter(f"codec.ops.{c.name}").inc()
        m.counter("codec.bytes.logical").inc(logical)
        m.counter("codec.bytes.wire").inc(wire)
        m.counter("codec.bytes_saved").inc(max(logical - wire, 0))
        if logical:
            m.gauge("codec.ratio").set(round(wire / logical, 4))
        m.gauge("codec.impl.native").set(
            1 if self._codec_kernel is not None else 0)
        m.histogram("codec.kernel.seconds").observe(self._op_ck_time)
        if res is not None and res.size:
            m.histogram("codec.feedback.norm").observe(
                float(np.abs(res).mean()))

    # ------------------------------------------------------------------
    # schedule selection (rabit_tpu/sched/)
    # ------------------------------------------------------------------
    def _ring_crossover(self) -> int:
        """Static tree/ring byte crossover: the configured
        rabit_ring_threshold_bytes, else the module default (kept as a
        module global so tests/benches can pin it process-wide)."""
        return (self._ring_threshold if self._ring_threshold is not None
                else TREE_RING_CROSSOVER_BYTES)

    def _static_schedule(self, nbytes: int) -> "sched_mod.Schedule":
        if nbytes <= self._ring_crossover() or self._world == 2:
            return sched_mod.TREE
        return sched_mod.RING

    def _pick_schedule(self, nbytes: int, op: ReduceOp,
                       logical_nbytes: Optional[int] = None,
                       pick_codec: str = "none") -> "sched_mod.Schedule":
        """Resolve the schedule for one dispatch point.  Every input is
        replicated across ranks (payload size, op, world, topology
        handout, the uniform rabit_sched/threshold/tuning-cache config),
        so all ranks pick the same algorithm — a collective decision,
        like bucket boundaries.

        Two size domains, deliberately distinct: ``nbytes`` is the TRUE
        wire size (what the static crossover and ``applies()`` reason
        about), while the MEASUREMENT lookups — the live directive and
        the tuning cache — key by ``logical_nbytes``, because spans
        (`_op_done`) and bench rows (collectives_bench's per-size
        table) both record logical payload sizes.  ``pick_codec`` is
        THIS op's effective wire format: a ``codec=False`` or
        ineligible op in an int8 job answers from the full-width rows,
        never the codec's."""
        logical = logical_nbytes if logical_nbytes is not None else nbytes
        name = self._sched_name
        if self._sched_live and name in ("static", "auto"):
            # Live directive from the tracker's adaptive controller:
            # the freshest measurement wins over the static crossover
            # and the offline cache — but never over an explicitly
            # FORCED schedule name, and only where it applies (the
            # fallback below keeps a stale directive from deadlocking).
            # Codec-scoped like the cache: a plain entry's evidence was
            # measured on the JOB's codec wire, a slashed
            # ``name/codec`` entry on its OWN named wire (which
            # ``_op_codec_for`` armed for this op) — either way the
            # entry answers only ops riding the wire it measured, so a
            # full-width opt-out/ineligible op — moving 2-4x the real
            # bytes — skips it and answers from its own format's rows.
            pick, dcodec = sched_mod.directive_entry(self._sched_live,
                                                     logical)
            want = dcodec if dcodec is not None else self._codec_label
            if pick is not None and pick_codec == want:
                s = sched_mod.SCHEDULES.get(pick)
                if s is not None and s.applies(self, nbytes):
                    return s
        if name == "static":
            return self._static_schedule(nbytes)
        if name == "auto":
            pick = (self._tuner.pick("allreduce", logical, self._world,
                                     self._transport_label,
                                     codec=pick_codec)
                    if self._tuner is not None else None)
            s = sched_mod.SCHEDULES.get(pick) if pick else None
            if s is not None and s.applies(self, nbytes):
                return s
            return self._static_schedule(nbytes)
        s = sched_mod.SCHEDULES[name]
        if s.applies(self, nbytes):
            return s
        return self._static_schedule(nbytes)

    def set_schedule(self, name: str) -> None:
        """Switch the selection mode at runtime (bench/tests hook).
        Like rabit_sched itself, the value MUST be uniform across ranks
        and changed only between collectives."""
        check(name in sched_mod.MODES,
              "schedule must be one of %s, got %r",
              "/".join(sched_mod.MODES), name)
        self._sched_name = name

    def _allreduce_dispatch(self, buf: np.ndarray, op: ReduceOp,
                            red_dtype=None,
                            logical_nbytes: Optional[int] = None,
                            pick_codec: str = "none") -> None:
        if buf.nbytes == 0:
            self._op_sched = None  # no wire phase: no schedule label
            return  # zero-size payloads move no wire bytes anywhere
        s = self._pick_schedule(buf.nbytes, op, logical_nbytes,
                                pick_codec)
        self._op_sched = s.name  # span label for the live plane
        if self._obs_on:
            self._metrics.counter(f"sched.pick.{s.name}").inc()
            self._metrics.counter(f"sched.pick.{s.name}.bytes").inc(
                buf.nbytes)
            if s.name != self._last_sched:
                # Trace on choice CHANGE only: per-op spans already
                # carry the stream, and flooding the bounded ring
                # buffer with one event per dispatch would evict them.
                self._trace.emit("sched", sched=s.name, nbytes=buf.nbytes,
                                 rank=self._rank, world=self._world,
                                 mode=self._sched_name)
                self._last_sched = s.name
        s.run(self, buf, op, red_dtype)

    def _children(self) -> list[int]:
        return [r for r in self._tree_links if r != self._parent]

    def _note_scratch(self, nbytes: int) -> None:
        if nbytes > self.scratch_peak_bytes:
            self.scratch_peak_bytes = nbytes

    def _drain_merge(self, peers: list[int], nitems: int, item: int,
                     merge, after_chunk=None) -> int:
        """Chunked concurrent drain-and-merge from ``peers``, the
        deadlock-sensitive inner pump shared by the tree collective and
        the hierarchical schedule's leader phase.

        Peers drain CONCURRENTLY through the transport pump (one slow
        peer no longer serializes its sibling), but merges stay in
        fixed peer order so the reduction order — and hence every
        result bit — matches the sequential protocol.  The
        rabit_reduce_buffer chunk budget divides across the peer
        buffers (chunk size never changes the per-link byte stream, so
        mixed-budget peers still interoperate); ``merge(off, n, src)``
        folds ``n`` items of received bytes ``src`` into the payload at
        item offset ``off``, and ``after_chunk(off, n)`` runs once per
        chunk window after its merges (the tree pump forwards the
        merged window to its parent there).  Returns the chunk size so
        callers can stream a symmetric follow-up phase.
        """
        denom = item * max(len(peers), 1)
        chunk = min(max(self._reduce_buffer // denom, 1), nitems)
        leases = [self._arena.take(chunk * item) for _ in peers]
        # scratch_peak reports the chunked working-set BUDGET (floored
        # at one chunk): peer-less ranks lease no scratch, but still
        # stream through chunk-sized windows, and the pre-existing
        # `0 < peak <= budget` contract (tests/workers/
        # check_reduce_buffer.py) holds on every rank.
        self._note_scratch(chunk * item * max(len(peers), 1))
        try:
            for off in range(0, nitems, chunk):
                n = min(chunk, nitems - off)
                if len(peers) == 1:
                    self._recv(peers[0], n * item, leases[0][: n * item])
                elif peers:
                    self._recv_all(peers, n * item, leases)
                for ci in range(len(peers)):
                    merge(off, n, leases[ci][: n * item])
                if after_chunk is not None:
                    after_chunk(off, n)
        finally:
            for lease in leases:
                self._arena.give(lease)
        return chunk

    def _tree_chunked(self, view: memoryview, nitems: int, item: int,
                      merge) -> None:
        """Two-phase chunked tree collective, shared by the built-in and
        custom allreduce paths.

        Chunked to the rabit_reduce_buffer budget in two strictly
        one-directional phases (all chunks up, then all chunks down):
        blocking sockets cannot deadlock, chunks stream across tree
        levels, and the per-link byte stream matches the unchunked
        protocol, so peers with different budgets interoperate.
        ``merge(off, n, src)`` folds ``n`` items of received bytes
        ``src`` into the payload at item offset ``off``.

        Sampled-op tracing files one "hop" record per phase (up-drain,
        down-broadcast), keyed by the parent link — the link a non-root
        rank actually waits on in both phases; the root keys by its
        first child (the link its pump drives).  Small worlds default
        to this schedule, so the causal timeline covers them too.
        """
        children = self._children()
        traced = self._op_traced
        hop_peer = self._parent if self._parent != P.NONE else (
            children[0] if children else -1)
        t_ph = time.perf_counter() if traced else 0.0
        send_up = None
        if self._parent != P.NONE:
            def send_up(off: int, n: int) -> None:
                self._send(self._parent,
                           view[off * item:(off + n) * item])
        # Phase 1: reduce up.
        chunk = self._drain_merge(children, nitems, item, merge,
                                  after_chunk=send_up)
        if traced:
            self._trace_hop("hop", hop_peer, nitems * item,
                            time.perf_counter() - t_ph)
            t_ph = time.perf_counter()
        # Phase 2: broadcast down.
        for off in range(0, nitems, chunk):
            n = min(chunk, nitems - off)
            if self._parent != P.NONE:
                self._recv(self._parent, n * item,
                           view[off * item:(off + n) * item])
            for r in children:
                self._send(r, view[off * item:(off + n) * item])
        if traced:
            self._trace_hop("hop", hop_peer, nitems * item,
                            time.perf_counter() - t_ph)

    def _tree_allreduce(self, buf: np.ndarray, op: ReduceOp,
                        red_dtype=None) -> None:
        """Reduce up the binary tree, broadcast the result down.

        ``red_dtype`` decouples the element type the merge runs in from
        the transport array's dtype (the bf16 wire path moves uint16
        bytes but reduces in bf16); None means they coincide.
        """
        flat = buf.reshape(-1)
        if flat.nbytes == 0:
            return  # zero-size payloads move no wire bytes on any rank
        red = red_dtype if red_dtype is not None else flat.dtype
        rflat = flat.view(red)

        def merge(off: int, n: int, src: memoryview) -> None:
            self._wire_merge(op, rflat, off, n,
                             np.frombuffer(src, dtype=red, count=n))

        self._tree_chunked(memoryview(flat).cast("B"), len(flat),
                           flat.itemsize, merge)

    def _ring_allreduce(self, buf: np.ndarray, op: ReduceOp,
                        red_dtype=None) -> None:
        """Bandwidth-optimal ring (the pump itself lives in
        rabit_tpu/sched/ring.py, generalized to sub-rings for the
        hierarchical schedule's leader phase)."""
        sched_mod.ring_allreduce(self, buf, op, red_dtype)

    def allreduce_custom(self, buf: np.ndarray, reducer, prepare_fun=None
                         ) -> np.ndarray:
        """Tree-fold custom allreduce: the Python ``reducer(dst, src)``
        merges per tree edge, O(log n) payload hops — replacing the
        interface's allgather-and-fold default (O(world x payload)), and
        matching the native engine's TreeAllreduceFn shape on the wire
        (reference analogue: ReduceHandle, include/rabit/engine.h:
        215-253).  Chunked row-wise to the reduce-buffer budget like
        _tree_allreduce; the reducer must be associative+commutative
        (merge order is tree order).
        """
        self._fence()
        return self._allreduce_custom_blocking(buf, reducer, prepare_fun)

    def _allreduce_custom_blocking(self, buf: np.ndarray, reducer,
                                   prepare_fun=None) -> np.ndarray:
        if prepare_fun is not None:
            prepare_fun()
        if self._world == 1:
            return buf
        if not self._obs_on:
            return self._allreduce_custom_impl(buf, reducer)
        t0 = time.perf_counter()
        out = self._allreduce_custom_impl(buf, reducer)
        self._op_done("allreduce_custom", buf.nbytes, t0)
        return out

    def _allreduce_custom_impl(self, buf: np.ndarray, reducer) -> np.ndarray:
        # Custom allreduces always ride the tree fold — label the span
        # honestly instead of leaking the previous dispatch's choice.
        # Never codec'd: the Python reducer owns the byte semantics.
        self._op_sched = "tree"
        self._op_wire = "none"
        rows = buf.shape[0] if buf.ndim > 0 else buf.size
        check(rows > 0, "allreduce_custom: empty buffer")
        if buf.nbytes == 0:
            return buf  # zero-size rows: nothing to merge or move
        row_shape = buf.shape[1:] if buf.ndim > 1 else ()
        flat = buf.reshape(rows, -1)
        item = flat.shape[1] * flat.itemsize  # bytes per axis-0 row
        dst_rows = buf.reshape((rows,) + row_shape)

        def merge(off: int, n: int, src: memoryview) -> None:
            rows_in = np.frombuffer(src, dtype=buf.dtype,
                                    count=n * flat.shape[1])
            reducer(dst_rows[off:off + n], rows_in.reshape((n,) + row_shape))

        self._tree_chunked(memoryview(flat).cast("B"), rows, item, merge)
        return buf

    def broadcast(self, data: Optional[bytes], root: int) -> bytes:
        self._fence()
        return self._broadcast_blocking(data, root)

    def _broadcast_blocking(self, data: Optional[bytes], root: int) -> bytes:
        if self._world == 1:
            check(data is not None, "broadcast: root rank must supply data")
            return data
        if not self._obs_on:
            return self._bcast_impl(data, root)
        t0 = time.perf_counter()
        out = self._bcast_impl(data, root)
        self._op_done("broadcast", len(out), t0)
        return out

    def _bcast_impl(self, data: Optional[bytes], root: int) -> bytes:
        """Uninstrumented tree flood (also the robust layer's recovery
        serving transport, which must not count as a user op)."""
        if self._rank == root:
            check(data is not None, "broadcast: root rank must supply data")
            header = struct.pack("<Q", len(data))
            view = memoryview(data)
            # Header + first chunk coalesce into one scatter-gather
            # write per link (the payload is resident at the root);
            # the byte stream per link is unchanged.
            for r in self._tree_links:
                self._sendv(r, header, view[:CHUNK_BYTES])
            for off in range(CHUNK_BYTES, len(data), CHUNK_BYTES):
                chunk = view[off:off + CHUNK_BYTES]
                for r in self._tree_links:
                    self._send(r, chunk)
            return data
        # Non-root: the payload arrives on exactly one tree link — the
        # first hop on the tree path toward the root, computable locally
        # (no probing needed, unlike the reference's in-link slot scan).
        # Chunk-pipelined: each chunk is forwarded downstream as soon as
        # it arrives, so the payload streams through the tree instead of
        # paying full-payload latency per level (same idea as the
        # reference's per-link ring buffers, src/allreduce_base.cc:
        # 500-588; byte stream per link is unchanged).
        src = self._toward(root)
        raw = self._recv(src, 8)
        (size,) = struct.unpack("<Q", bytes(raw))
        payload = memoryview(bytearray(size))
        header = struct.pack("<Q", size)
        downstream = [r for r in self._tree_links if r != src]
        for r in downstream:
            self._send(r, header)
        for off in range(0, size, CHUNK_BYTES):
            end = min(off + CHUNK_BYTES, size)
            self._recv(src, end - off, payload[off:end])
            for r in downstream:
                self._send(r, payload[off:end])
        return bytes(payload)

    def _toward(self, root: int) -> int:
        """First hop on the binary-heap-tree path from this rank to ``root``.

        Walk ``root``'s ancestor chain (indices strictly decrease); if it
        passes through this rank, the hop is the child we came through,
        else it is our parent.
        """
        r, prev = root, P.NONE
        while r > self._rank:
            prev = r
            r = (r - 1) // 2
        return prev if r == self._rank else self._parent

    def allgather(self, buf: np.ndarray) -> np.ndarray:
        self._fence()
        return self._allgather_blocking(buf)

    def _allgather_blocking(self, buf: np.ndarray) -> np.ndarray:
        if self._world == 1:
            return buf[None]
        if not self._obs_on:
            return self._allgather_impl(buf)
        t0 = time.perf_counter()
        out = self._allgather_impl(buf)
        self._op_done("allgather", out.nbytes, t0)
        return out

    def _allgather_impl(self, buf: np.ndarray) -> np.ndarray:
        """Ring all-gather: n-1 steps, each forwarding the newest block."""
        n = self._world
        out = np.empty((n,) + buf.shape, dtype=buf.dtype)
        out[self._rank] = buf
        for s in range(n - 1):
            send_b = (self._rank - s) % n
            recv_b = (self._rank - s - 1) % n
            self._exchange(
                self._ring_next, memoryview(out[send_b]).cast("B"),
                self._ring_prev, memoryview(out[recv_b]).cast("B"))
        return out

    # ------------------------------------------------------------------
    # async collectives: progress thread + small-op bucket fusion
    # ------------------------------------------------------------------
    # One background progress thread owns the links while async ops are
    # in flight; queued ops run strictly in issue order, so the wire (and
    # any robust-protocol layer above) sees exactly the op sequence a
    # blocking caller would produce.  Blocking entry points _fence()
    # first, which also flushes the coalescing bucket — mixing the two
    # styles is always safe, never reordered.

    def _ensure_pump(self) -> None:
        if self._aq_thread is None:
            self._aq_thread = threading.Thread(
                target=self._pump, name="rabit-async-pump", daemon=True)
            self._aq_thread.start()

    def _stop_pump(self) -> None:
        t = self._aq_thread
        if t is None:
            return
        with self._aq_cv:
            self._aq.append(None)
            self._aq_cv.notify_all()
        t.join(timeout=30)
        self._aq_thread = None

    def _pump(self) -> None:
        try:
            while True:
                with self._aq_cv:
                    while not self._aq:
                        self._aq_cv.wait()
                    item = self._aq.popleft()
                if item is None:
                    return
                fn, handles = item
                try:
                    fn()
                except Exception as e:  # noqa: BLE001 — surfaces at wait()
                    self._async_fail(e, handles)
                except BaseException as e:  # pump-killing failure
                    self._async_fail(e, handles)
                    raise
                finally:
                    with self._aq_cv:
                        self._aq_inflight -= 1
                        if self._obs_on:
                            self._metrics.gauge("async.queue_depth").set(
                                self._aq_inflight)
                        self._aq_cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — pump death: poison,
            self._poison_pending(e)  # never a downstream hang

    def _poison_pending(self, cause: BaseException) -> None:
        """The pump thread is dying: every queued (and future) async op
        can never run.  Fail their handles so ``wait()`` raises
        :class:`AsyncPumpError` instead of hanging forever, and wake
        any ``_fence()`` waiter."""
        err = AsyncPumpError(f"async progress pump died: "
                             f"{type(cause).__name__}: {cause}")
        err.__cause__ = cause
        self._log.error("async progress pump died (%s: %s); poisoning "
                        "%d queued op group(s)", type(cause).__name__,
                        cause, len(self._aq))
        if self._obs_on:
            self._metrics.counter("async.pump_deaths").inc()
            self._trace.emit("async", phase="pump_death", rank=self._rank,
                             error=type(cause).__name__)
        with self._aq_cv:
            self._pump_error = err
            drained = list(self._aq)
            self._aq.clear()
            self._aq_inflight = 0
            self._aq_cv.notify_all()
        for item in drained:
            if item is None:
                continue
            for h in item[1]:
                if not h.done():
                    h._fail(err)

    def _async_fail(self, exc: BaseException, handles: tuple) -> None:
        """Progress-thread failure path: no bare thread tracebacks — the
        error travels through the structured logger + event trace and
        re-raises at the caller's ``wait()`` (a link failure surfaces
        there as :class:`LinkError`, same as the blocking path)."""
        self._log.warn("async collective failed in the progress thread: "
                       "%s: %s", type(exc).__name__, exc)
        if self._obs_on:
            self._metrics.counter("async.errors").inc()
            self._trace.emit("async", phase="error", rank=self._rank,
                             error=type(exc).__name__)
        for h in handles:
            if not h.done():
                h._fail(exc)
        if isinstance(exc, WorldChangedError):
            # Every queued op was issued against the old world: fail
            # them all NOW with the same typed error (their issue-order
            # slots can never run), but keep the pump alive — after the
            # app reloads the checkpoint the async stream is usable
            # again, unlike a pump death.
            with self._aq_cv:
                drained = [it for it in self._aq if it is not None]
                self._aq = collections.deque(
                    it for it in self._aq if it is None)
                self._aq_inflight -= len(drained)
                # Realign the wait cursor past every drained slot: the
                # app catches the rescale at ONE wait() and abandons
                # the other failed handles (their wait() still raises
                # the stored error, as an idempotent re-wait) — the
                # first op issued after the reload must not trip the
                # issue-order check on slots that can never run.  Under
                # _aq_cv so a concurrent _before_wait's check-and-set
                # cannot clobber the realignment back down.
                self._wait_idx = self._issue_idx
                self._aq_cv.notify_all()
            for _fn, hs in drained:
                for h in hs:
                    if not h.done():
                        h._fail(exc)

    def _submit(self, fn: Callable[[], None], handles: tuple) -> None:
        # The pump-death check and the enqueue must be one atomic
        # section: _poison_pending drains the queue under this same
        # lock, so an item appended here is either drained by the
        # poison pass or observed the error first — never enqueued
        # behind a pump that already exited.
        with self._aq_cv:
            if self._pump_error is None:
                self._ensure_pump()
                self._aq.append((fn, handles))
                self._aq_inflight += 1
                if self._obs_on:
                    self._metrics.gauge("async.queue_depth").set(
                        self._aq_inflight)
                self._aq_cv.notify_all()
                return
            err = self._pump_error
        # The pump is dead; the op can never run.  Poison the handles
        # at issue so wait() raises immediately.
        for h in handles:
            if not h.done():
                h._fail(err)

    def _fence(self) -> None:
        """Drain the async stream: flush the pending bucket and wait for
        every queued op to finish.  Called by every blocking collective,
        checkpoint and shutdown (never from the pump itself)."""
        if self._pending is not None:
            self._flush_bucket()
        if self._aq_thread is None:
            return
        with self._aq_cv:
            while self._aq_inflight:
                self._aq_cv.wait()

    def _new_handle(self) -> CollectiveHandle:
        h = CollectiveHandle(on_wait=self._before_wait)
        h._issue_index = self._issue_idx
        h._t_submit = time.perf_counter()
        h._t_done = None
        self._issue_idx += 1
        return h

    def _resolve_handle(self, h: CollectiveHandle, result) -> None:
        h._t_done = time.perf_counter()
        h._resolve(result)

    def _before_wait(self, h: CollectiveHandle) -> None:
        idx = h._issue_index
        # Check-and-advance under _aq_cv: the pump's rescale drain
        # realigns _wait_idx concurrently, and an unlocked read-modify-
        # write here could clobber that realignment back down.
        with self._aq_cv:
            if idx > self._wait_idx:
                raise AsyncOrderError(
                    f"async handles must be waited in issue order: handle "
                    f"#{idx} waited before handle #{self._wait_idx}")
            if idx < self._wait_idx:
                return  # idempotent re-wait
            self._wait_idx = idx + 1
        if self._pending is not None:
            self._flush_bucket()
        if self._obs_on:
            now = time.perf_counter()
            end = h._t_done if h._t_done is not None else now
            # Overlap: how long the op ran in the background before the
            # caller blocked on it (the win over the blocking path).
            self._metrics.histogram("async.overlap.seconds").observe(
                max(min(end, now) - h._t_submit, 0.0))

    def allreduce_async(
        self,
        buf: np.ndarray,
        op: ReduceOp,
        prepare_fun: Optional[Callable[[], None]] = None,
        fuse: bool = True,
        codec: bool = True,
    ) -> CollectiveHandle:
        """``fuse=False`` is the lone-op escape hatch: a bucketed op
        only reaches the wire when its bucket flushes (next incompatible
        op, ``wait()``, or a fence), so a latency-sensitive op with no
        stream behind it should opt out of coalescing to start
        immediately and actually overlap the caller's compute.
        ``codec=False`` opts this op out of an armed lossy wire codec
        (full-precision classic bytes).  Both flags are program order,
        hence deterministic across ranks."""
        if self._world == 1:
            return CollectiveHandle.resolved(
                self.allreduce(buf, op, prepare_fun, codec))
        h = self._new_handle()
        if self._obs_on:
            self._metrics.counter("async.ops").inc()
        flat = buf.reshape(-1)
        if fuse and 0 < flat.nbytes <= self._bucket_bytes:
            self._bucket_add(flat, buf, op, prepare_fun, h, codec)
        else:
            self._flush_bucket()
            self._submit(lambda: self._resolve_handle(
                h, self._allreduce_blocking(buf, op, prepare_fun, codec)),
                (h,))
        return h

    def allgather_async(self, buf: np.ndarray) -> CollectiveHandle:
        if self._world == 1:
            return CollectiveHandle.resolved(self.allgather(buf))
        h = self._new_handle()
        if self._obs_on:
            self._metrics.counter("async.ops").inc()
        self._flush_bucket()
        self._submit(lambda: self._resolve_handle(
            h, self._allgather_blocking(buf)), (h,))
        return h

    def _bucket_add(self, flat: np.ndarray, buf: np.ndarray, op: ReduceOp,
                    prepare_fun, h: CollectiveHandle,
                    codec: bool = True) -> None:
        p = self._pending
        # The codec flag joins op/dtype as a bucket-compatibility key:
        # a fused wire op has ONE wire format, so a precision-opted-out
        # member must never share a bucket with codec-eligible ones.
        if p is not None and (p["op"] != op or p["dtype"] != flat.dtype
                              or p["codec"] != codec
                              or p["nbytes"] + flat.nbytes
                              > self._bucket_bytes):
            self._flush_bucket()
            p = None
        if p is None:
            p = self._pending = {"op": op, "dtype": flat.dtype,
                                 "codec": codec, "nbytes": 0, "items": []}
        p["items"].append((flat, buf, prepare_fun, h))
        p["nbytes"] += flat.nbytes

    def _flush_bucket(self) -> None:
        p, self._pending = self._pending, None
        if p is None:
            return
        items, op, codec = p["items"], p["op"], p["codec"]
        if len(items) == 1:
            flat, buf, prep, h = items[0]
            self._submit(lambda: self._resolve_handle(
                h, self._allreduce_blocking(buf, op, prep, codec)), (h,))
            return
        self._submit(lambda: self._fused_allreduce_exec(items, op, codec),
                     tuple(it[3] for it in items))

    def _record_fusion(self, nmembers: int, nbytes: int, t0: float,
                       replayed: bool = False) -> None:
        self._metrics.counter("async.fused.buckets").inc()
        self._metrics.counter("async.fused.members").inc(nmembers)
        self._metrics.counter("async.fused.bytes").inc(nbytes)
        self._op_done("allreduce_fused", nbytes, t0, replayed=replayed)

    @staticmethod
    def _scatter_fused(flats: list[np.ndarray], work: np.ndarray) -> None:
        off = 0
        for f in flats:
            f[:] = work[off:off + len(f)]
            off += len(f)

    def _fused_allreduce_exec(self, items: list, op: ReduceOp,
                              codec_ok: bool = True) -> None:
        """Runs ON the progress thread: one wire op for a whole bucket
        of small same-op/same-dtype allreduces.  The robust engine
        overrides this with the full consensus/cache/replay protocol
        (one seqno per bucket)."""
        t0 = time.perf_counter() if self._obs_on else 0.0
        for _flat, _buf, prep, _h in items:
            if prep is not None:
                prep()
        flats = [it[0] for it in items]
        self._fused_wire(flats, op, codec_ok)
        if self._obs_on:
            self._record_fusion(len(items),
                                sum(f.nbytes for f in flats), t0)
        for _flat, buf, _prep, h in items:
            self._resolve_handle(h, buf)

    def _member_rides_tree(self, flat: np.ndarray, op: ReduceOp,
                           codec_ok: bool = True) -> bool:
        """Would this member solo on the tree?  Classified on the WIRE
        size — the same quantity `_allreduce_impl` dispatches on after
        the codec encode (codec.wire_nbytes, the honest ratio; the
        historical hardcoded `//= 2` bf16 special case is gone) — so a
        member takes the identical algorithm (and reduction order)
        fused or solo."""
        if self._world == 2:
            return True
        nbytes = flat.nbytes
        if codec_ok:
            nbytes = self._solo_wire_nbytes(flat.dtype, op, nbytes)
        return nbytes <= self._ring_crossover()

    def _fused_wire(self, flats: list[np.ndarray], op: ReduceOp,
                    codec_ok: bool = True) -> None:
        """In-place fused reduction of same-op/same-dtype member arrays.

        Bit-transparency is the design constraint: fusion must not
        change any member's element-wise reduction ORDER.  Tree order is
        position-independent (children-then-parent for every element),
        so members that would solo on the tree reduce as one
        concatenated tree op — forced onto the tree even when the
        concatenation crosses the tree/ring size threshold; ring order
        depends on a member's own block partition, so ring-class members
        ride a SEGMENTED ring (per-member block bounds, vectored
        exchanges) and come out bit-identical to their solo runs.

        Under a non-static schedule mode (forced or auto-tuned) the
        bucket instead concatenates whole and rides the selected
        schedule for the concatenated size: the new peer patterns
        (halving/swing/hier) partition by block position, so per-member
        solo order cannot be preserved through fusion anyway — results
        are exact for exactly-representable payloads (the documented
        envelope, doc/performance.md) and deterministic either way, so
        pyrobust replay still serves identical bits.

        An armed BLOCK-SCALED codec also takes the concatenate path
        (when the concatenation is codec-eligible): its wire elements
        are whole quantization blocks, not per-member views, and the
        documented accuracy envelope already replaces bit-transparency
        — one encode over the concatenation beats per-member scales.
        """
        c = self._codec
        block_codec = (codec_ok and c is not None and not c.elementwise
                       and c.eligible(
                           flats[0].dtype, op,
                           int(sum(f.nbytes for f in flats))))
        if self._sched_name != "static" or block_codec:
            if len(flats) == 1:
                self._allreduce_impl(flats[0], op, codec_ok)
            else:
                work = np.concatenate(flats)
                self._allreduce_impl(work, op, codec_ok)
                self._scatter_fused(flats, work)
            return
        tree = [f for f in flats
                if self._member_rides_tree(f, op, codec_ok)]
        ring = [f for f in flats
                if not self._member_rides_tree(f, op, codec_ok)]
        # Span labels (live plane): a mixed bucket keeps the label of
        # its LAST wire phase — approximate by design; per-member exact
        # labels would need one span per member for one wire op.  The
        # wire label on the static path is the per-member bf16 cast
        # (block codecs took the concatenate branch above).
        self._op_sched = "ring" if ring else "tree"
        self._op_wire = ("bf16" if codec_ok and self._wire_eligible(
            flats[0].dtype, op, flats[0].nbytes) else "none")
        if len(tree) == 1:
            self._allreduce_impl(tree[0], op, codec_ok)
        elif tree:
            work = np.concatenate(tree)
            wire = self._wire_cast(work, op) if codec_ok else None
            if wire is not None:
                w, red = wire
                self._tree_allreduce(w, op, red)
                # codec telemetry: the static fused paths bypass
                # _allreduce_impl, so they file their own counts —
                # else the bulk fused traffic would vanish from the
                # codec.* counters exactly where the codec matters.
                self._note_codec_op(self._codec, work.nbytes, w.nbytes)
                work = w.view(red).astype(np.float32)
            else:
                self._tree_allreduce(work, op)
            self._scatter_fused(tree, work)
        if ring:
            self._ring_allreduce_fused(ring, op, codec_ok)

    def _ring_allreduce_fused(self, flats: list[np.ndarray],
                              op: ReduceOp,
                              codec_ok: bool = True) -> None:
        wires = ([self._wire_cast(f, op) for f in flats] if codec_ok
                 else [None for _ in flats])
        if wires[0] is None:  # eligibility is uniform (same op/dtype)
            self._ring_segmented(flats, op, flats[0].dtype)
            return
        transports = [w for w, _red in wires]
        red = wires[0][1]
        self._ring_segmented(transports, op, red)
        self._note_codec_op(self._codec,
                            int(sum(f.nbytes for f in flats)),
                            int(sum(t.nbytes for t in transports)))
        for f, t in zip(flats, transports):
            f[:] = t.view(red).astype(np.float32)

    def _ring_segmented(self, tflats: list[np.ndarray], op: ReduceOp,
                        red) -> None:
        """Fused multi-member segmented ring (pump extracted to
        rabit_tpu/sched/ring.py with the solo ring)."""
        sched_mod.ring_segmented(self, tflats, op, red)

    # ------------------------------------------------------------------
    # checkpoints (non-fault-tolerant: process-local, like the reference
    # base engine — the robust layer adds replication/recovery)
    # ------------------------------------------------------------------
    def load_checkpoint(self):
        self._fence()
        return (self._version, self._global, self._local)

    def checkpoint(self, global_model, local_model=None, lazy_global=None):
        self._fence()
        if global_model is None and lazy_global is not None:
            global_model = lazy_global()
        self._global = global_model
        self._local = local_model
        self._version += 1

    @property
    def version_number(self) -> int:
        return self._version
