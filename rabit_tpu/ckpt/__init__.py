"""rabit_tpu.ckpt — the durable checkpoint tier.

On-disk versioned checkpoints below the robust engine's in-memory
replicas: elected writer ranks persist every committed version to
``rabit_ckpt_dir`` (atomic tmp+fsync+rename, CRC32-stamped blobs,
per-writer ``manifest.json``, bounded ``rabit_ckpt_keep`` retention),
and the checkpoint-load path cold-resumes from the newest valid on-disk
version when NO live rank holds one — a kill-all-ranks restart resumes
at the last committed version instead of version 0
(doc/fault_tolerance.md "Durable checkpoints & heartbeats").
"""
from rabit_tpu.ckpt.store import (CheckpointSkewError, CheckpointStore,
                                  DiskCheckpoint, expand_dir, pack_blob,
                                  unpack_blob)

__all__ = [
    "CheckpointSkewError",
    "CheckpointStore",
    "DiskCheckpoint",
    "expand_dir",
    "pack_blob",
    "unpack_blob",
]
