"""Durable checkpoint tier: versioned on-disk model blobs.

The in-memory recovery protocol (engine/robust.py) survives any failure
that leaves at least one live rank holding the committed checkpoint.  A
*correlated* loss — full-pod preemption, "every replica of version N
died", a supervisor restarting the whole world — previously restarted
the job at version 0.  This module is the tier below the RAM replicas:
elected writer ranks persist each committed ``(version, global, local)``
state to ``rabit_ckpt_dir``, and the engine's checkpoint-load path falls
back to the newest *valid* on-disk version when no live rank has one
(doc/fault_tolerance.md "Durable checkpoints & heartbeats").

Durability discipline (writer side):

* Every file lands via **tmp-file + fsync + rename** — a writer killed
  at any instruction leaves either the old file or the new file, never
  a torn one.  The blob is renamed before the manifest referencing it,
  so a manifest entry always names a fully-written blob.
* Blobs are **CRC32-stamped** end to end; the loader verifies before
  serving and silently falls back to the next-older version on a
  corrupt or truncated blob.
* Each writer owns its own manifest (``manifest.json`` for rank 0,
  ``manifest.r<N>.json`` otherwise): there is no cross-process
  read-modify-write anywhere, so concurrent writers on a shared
  filesystem never race.
* Bounded retention: ``rabit_ckpt_keep`` newest versions per writer;
  pruning rewrites the manifest first, then deletes the blobs it no
  longer references.

Loader side: candidates are collected from every manifest **plus** a
direct scan for orphan blobs (a writer that died between the blob
rename and the manifest rename leaves a valid, unreferenced blob — it
still counts), then validated newest-first.
"""
from __future__ import annotations

import glob
import json
import os
import struct
import zlib
from dataclasses import dataclass, field

from rabit_tpu.utils.checks import RabitError, log

_BLOB_MAGIC = 0x7AB1C902
_FORMAT = 1
_HEADER = struct.Struct("<IIIIII")  # magic, format, version, world, writer, nlocals
_U64 = struct.Struct("<Q")
_LOCAL_HDR = struct.Struct("<IQ")   # origin rank, blob length
_CRC = struct.Struct("<I")


class CheckpointSkewError(RabitError):
    """A rank's durable checkpoint is NEWER than the cluster-agreed one.

    Raised by a (re)joining rank when the version it would be served by
    the live world is older than a valid checkpoint on its own disk —
    the disk belongs to a different (or further-progressed) incarnation
    of the job, and silently accepting the stale cluster state would
    roll committed work backward without anyone noticing.  Carries both
    versions so the supervisor can decide which side is wrong."""

    def __init__(self, disk_version: int, agreed_version: int) -> None:
        super().__init__(
            f"durable checkpoint skew: disk holds committed version "
            f"{disk_version} but the cluster agreed on version "
            f"{agreed_version} — refusing to serve stale state")
        self.disk_version = int(disk_version)
        self.agreed_version = int(agreed_version)


@dataclass
class DiskCheckpoint:
    """One validated on-disk checkpoint (see :func:`unpack_blob`)."""

    version: int
    world: int
    writer: int
    global_blob: bytes
    locals: dict[int, bytes] = field(default_factory=dict)
    raw: bytes = b""  # the full CRC-stamped blob, re-servable as-is


def pack_blob(version: int, world: int, writer: int, global_blob: bytes,
              locals_: dict[int, bytes] | None = None) -> bytes:
    """Serialize one checkpoint into the self-describing CRC-stamped
    wire/disk format (shared by the on-disk files and the cold-restart
    serving broadcast)."""
    locals_ = locals_ or {}
    parts = [_HEADER.pack(_BLOB_MAGIC, _FORMAT, version, world, writer,
                          len(locals_)),
             _U64.pack(len(global_blob))]
    origins = sorted(locals_)
    for origin in origins:
        parts.append(_LOCAL_HDR.pack(origin, len(locals_[origin])))
    parts.append(global_blob)
    for origin in origins:
        parts.append(locals_[origin])
    body = b"".join(parts)
    return body + _CRC.pack(zlib.crc32(body) & 0xFFFFFFFF)


def unpack_blob(raw: bytes) -> DiskCheckpoint:
    """Parse + CRC-verify a blob produced by :func:`pack_blob`.
    Raises ``ValueError`` on any corruption (bad magic, truncation,
    CRC mismatch) — the loader turns that into fallback, the engine's
    install path into a loud error."""
    if len(raw) < _HEADER.size + _U64.size + _CRC.size:
        raise ValueError("checkpoint blob truncated")
    (crc,) = _CRC.unpack_from(raw, len(raw) - _CRC.size)
    body = raw[:-_CRC.size]
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise ValueError("checkpoint blob CRC mismatch")
    magic, fmt, version, world, writer, nlocals = _HEADER.unpack_from(body)
    if magic != _BLOB_MAGIC or fmt != _FORMAT:
        raise ValueError(f"bad checkpoint blob magic/format "
                         f"({magic:#x}/{fmt})")
    pos = _HEADER.size
    (glen,) = _U64.unpack_from(body, pos)
    pos += _U64.size
    local_hdrs = []
    for _ in range(nlocals):
        origin, llen = _LOCAL_HDR.unpack_from(body, pos)
        pos += _LOCAL_HDR.size
        local_hdrs.append((int(origin), int(llen)))
    if pos + glen + sum(l for _, l in local_hdrs) != len(body):
        raise ValueError("checkpoint blob length mismatch")
    global_blob = body[pos:pos + glen]
    pos += glen
    locals_: dict[int, bytes] = {}
    for origin, llen in local_hdrs:
        locals_[origin] = body[pos:pos + llen]
        pos += llen
    return DiskCheckpoint(int(version), int(world), int(writer),
                          global_blob, locals_, raw=bytes(raw))


def expand_dir(path: str, rank: int) -> str:
    """Expand the ``{rank}`` token so local multi-process jobs can
    emulate per-host disks with one ``rabit_ckpt_dir`` setting."""
    return path.replace("{rank}", str(rank))


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(dirname: str) -> None:
    """Make the renames themselves durable (best effort: some
    filesystems refuse O_RDONLY directory fsync)."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointStore:
    """One rank's view of a durable checkpoint directory.

    ``rank`` names this process for writer-side file ownership; any
    rank (writer or not) can load.  All writes are atomic-rename
    transactions, so killing a writer at ANY point leaves the store
    readable (possibly one version behind)."""

    def __init__(self, root: str, rank: int = 0, keep: int = 3) -> None:
        self.root = str(root)
        self.rank = int(rank)
        self.keep = max(int(keep), 1)
        os.makedirs(self.root, exist_ok=True)
        self._sweep_stale_tmps()

    def _sweep_stale_tmps(self) -> None:
        """Reap tmp files a killed predecessor of THIS rank left behind
        (crash between open and rename) so they cannot accumulate
        model-sized junk across preemptions.  Scoped to this rank's own
        file names and foreign pids — a concurrent writer of another
        rank mid-persist is never touched."""
        own = (f".v*.r{self.rank}.ckpt.tmp.*",
               f".{self.manifest_name}.tmp.*")
        pid_suffix = f".tmp.{os.getpid()}"
        for pattern in own:
            for path in glob.glob(os.path.join(self.root, pattern)):
                if path.endswith(pid_suffix):
                    continue  # this process's own in-flight write
                try:
                    os.remove(path)
                except OSError:
                    pass

    # -- naming --------------------------------------------------------
    def _blob_name(self, version: int) -> str:
        return f"v{version:08d}.r{self.rank}.ckpt"

    @property
    def manifest_name(self) -> str:
        return "manifest.json" if self.rank == 0 else \
            f"manifest.r{self.rank}.json"

    def _write_atomic(self, name: str, data: bytes) -> str:
        """tmp + fsync + rename; the only way bytes reach the store."""
        final = os.path.join(self.root, name)
        tmp = os.path.join(self.root, f".{name}.tmp.{os.getpid()}")
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        return final

    # -- writer side ---------------------------------------------------
    def persist(self, version: int, world: int, global_blob: bytes,
                locals_: dict[int, bytes] | None = None) -> str:
        """Durably persist one committed version; returns the blob path.

        Order matters for crash-safety: blob rename, (test crash seam),
        manifest rename, then pruning of blobs the new manifest no
        longer references."""
        raw = pack_blob(version, world, self.rank, global_blob, locals_)
        name = self._blob_name(version)
        path = self._write_atomic(name, raw)
        _fsync_dir(self.root)
        self._maybe_crash(version)
        entries = [e for e in self._read_manifest_entries(self.manifest_name)
                   if e.get("version") != version]
        entries.append({
            "version": int(version),
            "file": name,
            "size": len(raw),
            "crc": zlib.crc32(raw) & 0xFFFFFFFF,
            "fingerprint": zlib.crc32(global_blob) & 0xFFFFFFFF,
        })
        entries.sort(key=lambda e: e["version"], reverse=True)
        keep, drop = entries[:self.keep], entries[self.keep:]
        manifest = {"format": _FORMAT, "writer": self.rank,
                    "world": int(world), "entries": keep}
        self._write_atomic(self.manifest_name,
                           json.dumps(manifest, indent=1).encode())
        _fsync_dir(self.root)
        for e in drop:  # only after the manifest stopped naming them
            try:
                os.remove(os.path.join(self.root, e["file"]))
            except OSError:
                pass
        return path

    def _maybe_crash(self, version: int) -> None:
        """Deterministic torn-persist injection (tests): with
        ``RABIT_CKPT_CRASH="rank,version"`` the writer dies with the
        restart exit code after the blob rename but before the manifest
        rename — first life only, like a mock kill-point."""
        spec = os.environ.get("RABIT_CKPT_CRASH", "")
        if not spec or os.environ.get("RABIT_NUM_TRIAL", "0") != "0":
            return
        try:
            crash_rank, crash_version = (int(x) for x in spec.split(","))
        except ValueError:
            return
        if crash_rank == self.rank and crash_version == version:
            log("ckpt: injected writer death after blob rename "
                "(rank %d, v%d)", self.rank, version)
            os._exit(254)

    # -- loader side ---------------------------------------------------
    def _read_manifest_entries(self, name: str) -> list[dict]:
        try:
            with open(os.path.join(self.root, name)) as f:
                doc = json.load(f)
            entries = doc.get("entries", [])
            return [e for e in entries
                    if isinstance(e.get("version"), int) and e.get("file")]
        except (OSError, ValueError):
            return []

    def _candidates(self) -> list[tuple[int, str]]:
        """(version, filename) pairs from every manifest plus orphan
        blobs no manifest names, deduped, newest version first."""
        seen: dict[str, int] = {}
        for mpath in glob.glob(os.path.join(self.root, "manifest*.json")):
            for e in self._read_manifest_entries(os.path.basename(mpath)):
                seen.setdefault(e["file"], int(e["version"]))
        for bpath in glob.glob(os.path.join(self.root, "v*.ckpt")):
            name = os.path.basename(bpath)
            try:
                version = int(name[1:].split(".", 1)[0])
            except ValueError:
                continue
            seen.setdefault(name, version)
        return sorted(((v, f) for f, v in seen.items()),
                      key=lambda t: (-t[0], t[1]))

    def _load_file(self, name: str) -> DiskCheckpoint | None:
        try:
            with open(os.path.join(self.root, name), "rb") as f:
                raw = f.read()
            return unpack_blob(raw)
        except (OSError, ValueError) as e:
            log("ckpt: skipping invalid checkpoint blob %s (%s)", name, e)
            return None

    def load_latest(self) -> DiskCheckpoint | None:
        """Newest CRC-valid checkpoint, falling back to older versions
        past corrupt/truncated blobs; None when the store is empty or
        nothing validates."""
        for _version, name in self._candidates():
            dc = self._load_file(name)
            if dc is not None:
                return dc
        return None

    def load_version(self, version: int) -> DiskCheckpoint | None:
        """The newest *valid* blob of exactly ``version``, from any
        writer's manifest (or an orphan).  The elastic soak gate reads
        rescale-boundary versions with this for its segmented
        bit-identical reference comparison; ``None`` when that version
        is absent or nothing validates."""
        for v, name in self._candidates():
            if v != version:
                continue
            dc = self._load_file(name)
            if dc is not None:
                return dc
        return None

    def newest_version(self, min_version: int | None = None) -> int | None:
        """Version of the newest *valid* checkpoint (the skew-guard
        input); invalid blobs do not count.  ``min_version`` considers
        only candidates strictly above it — the skew guard passes the
        cluster-agreed version, so the common no-skew case touches no
        blob at all instead of CRC-scanning the full newest model on
        every recovery."""
        for version, name in self._candidates():
            if min_version is not None and version <= min_version:
                return None  # candidates are newest-first: all done
            dc = self._load_file(name)
            if dc is not None:
                return dc.version
        return None

    def versions(self) -> list[int]:
        """Distinct candidate versions, newest first (validity NOT
        checked — pair with :meth:`load_version`).  The serving plane's
        refresh poll walks this to find versions newer than the one it
        serves without CRC-scanning any blob."""
        out: list[int] = []
        for v, _name in self._candidates():
            if not out or out[-1] != v:
                out.append(v)
        return out

    def scan(self) -> list[dict]:
        """Inventory for tooling/tests: every candidate with its
        validity verdict."""
        out = []
        for version, name in self._candidates():
            dc = self._load_file(name)
            out.append({"version": version, "file": name,
                        "valid": dc is not None,
                        "writer": dc.writer if dc else None})
        return out
