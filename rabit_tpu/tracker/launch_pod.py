"""Multi-host launcher: one worker per TPU-VM host (or hostfile entry).

Equivalent of the reference's cluster launchers
(reference: tracker/rabit_mpi.py:25-40 — mpirun submission;
tracker/rabit_hadoop.py:96-160 — workers as Hadoop streaming mappers).
The TPU-native deployment unit is a pod slice: one worker process per
host, each owning that host's chips, with the tracker reachable over
DCN.  Submission is pluggable the same way the reference's
``fun_submit`` is (reference: tracker/rabit_tracker.py:264-270):

* ``ssh``  — start workers over ssh to each host in a hostfile (the
  classic cluster path; TPU VMs expose plain ssh).
* ``local``— subprocesses on this machine (testing / single host).

The tracker assigns ranks in connect order keyed by task id, so restarts
keep their rank (reference: tracker/rabit_tracker.py:60-65).

Usage:
    python -m rabit_tpu.tracker.launch_pod --hostfile hosts.txt -- \
        python train.py
    python -m rabit_tpu.tracker.launch_pod --local -n 4 -- python train.py
"""
from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
import threading

from rabit_tpu.tracker.tracker import Tracker


def _read_hostfile(path: str) -> list[str]:
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line.split()[0])
    return hosts


def launch_pod(cmd: list[str], hosts: list[str] | None = None,
               n_local: int = 0, tracker_host: str | None = None,
               ssh_opts: str = "", verbose: bool = False,
               watchdog_sec: float | None = None,
               max_wd_restarts: int = 10,
               pidfile_dir: str = "/tmp",
               max_restarts: int = 0,
               ckpt_dir: str | None = None,
               heartbeat_sec: float | None = None,
               restart_backoff_ms: float = 250.0,
               min_workers: int | None = None,
               max_workers: int | None = None,
               state_dir: str | None = None,
               job: str | None = None,
               obs_port: int | None = None) -> int:
    """Run ``cmd`` once per host (or n_local subprocesses).

    Returns 0 when every worker exits cleanly.  Unlike the keepalive
    demo launcher, kill-point restarts are the platform's job (the
    reference makes the same split: rabit_demo restarts, mpi/hadoop
    delegate, reference: guide/README.md "Fault Tolerance").

    ``watchdog_sec``: hung-worker detection, same contract as
    ``launch_local`` — when a rendezvous round stalls that long, the
    tracker reports the silent workers and the launcher kills AT MOST
    ONE per stall event (killing one unblocks its Gloo peers into
    recovery with their checkpoint replicas intact) and restarts it
    with an incremented ``RABIT_RELAUNCH``.  Remote workers are killed
    over ssh via the pidfile each one writes at startup (the launcher
    owns watchdog restarts even though kill-point restarts are
    delegated: the launcher caused the death).

    Durability knobs, same contract as ``launch_local`` so pod launches
    get the full stack: ``ckpt_dir``/``heartbeat_sec`` export
    ``RABIT_CKPT_DIR``/``RABIT_HEARTBEAT_SEC`` to every worker (the
    heartbeat also arms the tracker's proactive failure detector, whose
    dead verdicts kill the hung remote over ssh and restart it), and
    ``max_restarts`` is the supervisor budget — a signal-killed worker
    (preemption, crash, kill-all) is relaunched with capped-exponential
    backoff instead of aborting the job; with a durable tier configured
    even whole-pod loss resumes from the last committed version.

    ``min_workers`` / ``max_workers`` / ``state_dir``: elastic
    membership + tracker HA, same contract as ``launch_local`` — the
    tracker admits late joiners up to the ceiling, heartbeat deaths
    scale the world down to the floor at checkpoint-commit boundaries
    (a signal-killed worker past its restart budget *leaves* instead
    of failing the job), workers get ``RABIT_ELASTIC=1``, and the
    control-plane state is journaled to ``state_dir`` so a restarted
    tracker resumes the job (doc/fault_tolerance.md "Elastic
    membership & tracker HA").
    """
    import os
    import time
    import uuid

    from rabit_tpu.tracker.launch_local import (is_dead_exit,
                                                is_watchdog_exit,
                                                make_dead_killer,
                                                make_stall_killer,
                                                restart_delay_ms)

    world = len(hosts) if hosts else n_local
    assert world > 0, "no hosts / workers requested"
    if job is not None:
        from rabit_tpu.tracker import protocol as P

        P.require_valid_job_id(job)
    # remote workers need a routable tracker address; local ones loopback
    from rabit_tpu.utils.net import routable_ip

    job_tag = uuid.uuid4().hex[:10]
    live: dict[int, subprocess.Popen] = {}
    started: dict[int, float] = {}
    watchdog_killed: set[int] = set()
    lock = threading.Lock()
    aborting = threading.Event()

    def _remote_pidfile(i: int) -> str:
        return f"{pidfile_dir}/rabit_pod_{job_tag}_{i}.pid"

    def _kill_worker(i: int, proc: subprocess.Popen) -> None:
        if hosts:
            # the local Popen is the ssh client; kill the REMOTE process
            # GROUP (the worker runs under setsid, so the pidfile pid is
            # its pgid — children die with it).  Best-effort: whatever
            # happens to the ssh leg, the local client must still die so
            # the keepalive loop can restart the worker.
            pidfile = _remote_pidfile(i)
            try:
                subprocess.run(
                    ["ssh"] + shlex.split(ssh_opts) + [
                        hosts[i],
                        f"kill -9 -$(cat {shlex.quote(pidfile)}) "
                        "2>/dev/null"],
                    timeout=30, check=False)
            finally:
                proc.kill()
        else:
            proc.kill()

    on_stall = make_stall_killer(world, live, started, lock,
                                 watchdog_killed, watchdog_sec,
                                 "launch_pod", kill_fn=_kill_worker)

    # Heartbeat dead verdicts use the same kill transport as the stall
    # watchdog (remote workers die over ssh via their pidfile) and the
    # same restart bookkeeping.
    on_dead = make_dead_killer(live, started, lock, watchdog_killed,
                               heartbeat_sec, "launch_pod",
                               kill_fn=_kill_worker)

    elastic = min_workers is not None or max_workers is not None
    tracker = Tracker(world, host=tracker_host
                      or (routable_ip() if hosts else "127.0.0.1"),
                      watchdog_sec=watchdog_sec,
                      on_stall=on_stall if watchdog_sec else None,
                      on_dead=on_dead if heartbeat_sec else None,
                      min_workers=min_workers, max_workers=max_workers,
                      state_dir=state_dir, obs_port=obs_port)
    tracker.start()
    codes: list[int] = [0] * world

    def spawn(i: int, relaunch: int) -> subprocess.Popen:
        env = tracker.worker_env(task_id=str(i), job=job)
        env["RABIT_RELAUNCH"] = str(relaunch)
        if ckpt_dir is not None:
            env.setdefault("RABIT_CKPT_DIR", str(ckpt_dir))
        if heartbeat_sec:
            env.setdefault("RABIT_HEARTBEAT_SEC", str(heartbeat_sec))
        if elastic:
            env.setdefault("RABIT_ELASTIC", "1")
        if hosts:
            env_prefix = " ".join(
                f"{k}={shlex.quote(v)}" for k, v in env.items())
            # remote workers mirror the launch cwd (TPU-VM images keep
            # homogeneous paths across a slice).  setsid + `echo $$;
            # exec` makes the pidfile pid both the worker AND its
            # process-group id, so the watchdog's group kill takes the
            # worker's children down with it.
            worker = " ".join(shlex.quote(c) for c in cmd)
            inner = (f"echo $$ > {shlex.quote(_remote_pidfile(i))} && "
                     f"exec env {env_prefix} {worker}")
            remote = (f"cd {shlex.quote(os.getcwd())} && "
                      f"exec setsid sh -c {shlex.quote(inner)}")
            full = ["ssh"] + shlex.split(ssh_opts) + [hosts[i], remote]
            if verbose:
                sys.stderr.write(f"[launch_pod] {full}\n")
            return subprocess.Popen(full)
        penv = dict(os.environ)
        penv.update(env)
        return subprocess.Popen(cmd, env=penv)

    def run_one(i: int) -> None:
        wd_restarts = 0
        sup_restarts = 0
        while not aborting.is_set():
            try:
                proc = spawn(i, wd_restarts + sup_restarts)
            except Exception as e:  # ssh/worker binary missing
                sys.stderr.write(
                    f"[launch_pod] worker {i} failed to start: {e}\n")
                codes[i] = 1
                break
            with lock:
                live[i] = proc
                started[i] = time.monotonic()
            code = proc.wait()
            with lock:
                live.pop(i, None)
                was_watchdog = i in watchdog_killed
                watchdog_killed.discard(i)
            if (was_watchdog
                    and is_watchdog_exit(code, remote=bool(hosts))
                    and wd_restarts < max_wd_restarts):
                wd_restarts += 1
                continue
            if (is_dead_exit(code, remote=bool(hosts))
                    and sup_restarts < max_restarts
                    and not aborting.is_set()):
                # Supervisor path: signal-killed (preempted/crashed)
                # worker — relaunch under the bounded backoff budget.
                sup_restarts += 1
                delay_ms = restart_delay_ms(sup_restarts,
                                            restart_backoff_ms)
                sys.stderr.write(
                    f"[launch_pod] supervisor: worker {i} died (exit "
                    f"{code}); relaunch #{sup_restarts}/{max_restarts} "
                    f"in {delay_ms:.0f} ms\n")
                sys.stderr.flush()
                time.sleep(delay_ms / 1000.0)
                continue
            if (elastic and is_dead_exit(code, remote=bool(hosts))
                    and not aborting.is_set()):
                # Elastic leave (same contract as launch_local): a
                # preempted worker past its restart budget departs —
                # the tracker scales the world down at the next commit
                # boundary instead of the job failing.  note_dead is
                # the only death signal without heartbeats armed (and
                # a dedup'd no-op with them).
                sys.stderr.write(
                    f"[launch_pod] elastic: worker {i} left the job "
                    f"(exit {code}); world scales down\n")
                sys.stderr.flush()
                tracker.note_dead(str(i), job=job)
                break
            codes[i] = code
            break
        # a permanent nonzero exit means the rendezvous barrier can never
        # fill — abort the job instead of letting peers wait forever
        # (same contract as launch_local)
        if codes[i] != 0 and not aborting.is_set():
            aborting.set()
            tracker.stop()
            with lock:
                for p in live.values():
                    p.terminate()

    threads = [threading.Thread(target=run_one, args=(i,))
               for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if not aborting.is_set():
        tracker.join(timeout=10)
    tracker.stop()
    return next((c for c in codes if c != 0), 0)


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="launch rabit_tpu workers across hosts (TPU pod slice)")
    ap.add_argument("--hostfile", help="file with one host per line")
    ap.add_argument("--local", action="store_true",
                    help="run workers as local subprocesses")
    ap.add_argument("-n", "--num-workers", type=int, default=0,
                    help="worker count for --local")
    ap.add_argument("--tracker-host", default=None,
                    help="address workers use to reach the tracker "
                         "(default: this host's primary interface)")
    ap.add_argument("--ssh-opts", default="",
                    help="extra options passed to ssh")
    ap.add_argument("--watchdog", type=float, default=None, metavar="SEC",
                    help="kill+restart workers that stall a rendezvous "
                         "round this long (hung-worker detection; remote "
                         "workers are killed over ssh)")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="supervisor budget: relaunch a signal-killed "
                         "worker (crash/preemption/kill-all) up to this "
                         "many times, backoff-paced; 0 disables")
    ap.add_argument("--ckpt-dir", default=None,
                    help="durable checkpoint tier (RABIT_CKPT_DIR): "
                         "writer ranks persist committed versions; a "
                         "cold restart resumes from disk — use a path "
                         "valid on every host ('{rank}' expands per "
                         "worker)")
    ap.add_argument("--heartbeat", type=float, default=None, metavar="SEC",
                    help="worker keepalive period (RABIT_HEARTBEAT_SEC); "
                         "arms the tracker's proactive failure detector "
                         "(hung remotes are killed over ssh + restarted)")
    ap.add_argument("--min-workers", type=int, default=None,
                    help="elastic floor: heartbeat-detected deaths scale "
                         "the world down (never below this) at the next "
                         "checkpoint-commit boundary")
    ap.add_argument("--max-workers", type=int, default=None,
                    help="elastic ceiling: late cmd=start registrants "
                         "join at the next rescale epoch, up to this "
                         "world size")
    ap.add_argument("--state-dir", default=None,
                    help="journal the tracker's control-plane state so "
                         "a restarted tracker resumes the job (tracker "
                         "HA)")
    ap.add_argument("--job", default=None, metavar="ID",
                    help="tenant name (rabit_job_id / RABIT_JOB_ID): "
                         "workers register under this job and their "
                         "logs/obs summaries carry it (doc/"
                         "fault_tolerance.md 'Multi-tenant tracker')")
    ap.add_argument("--obs-port", type=int, default=None,
                    help="serve the live telemetry plane while the job "
                         "runs: GET /metrics (Prometheus) + GET /status "
                         "(JSON) on this port; 0 = ephemeral "
                         "(doc/observability.md 'Live telemetry')")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("missing worker command")
    hosts = _read_hostfile(args.hostfile) if args.hostfile else None
    if not hosts and not args.local:
        ap.error("need --hostfile or --local")
    sys.exit(launch_pod(cmd, hosts=hosts, n_local=args.num_workers,
                        tracker_host=args.tracker_host,
                        ssh_opts=args.ssh_opts, verbose=args.verbose,
                        watchdog_sec=args.watchdog,
                        max_restarts=args.max_restarts,
                        ckpt_dir=args.ckpt_dir,
                        heartbeat_sec=args.heartbeat,
                        min_workers=args.min_workers,
                        max_workers=args.max_workers,
                        state_dir=args.state_dir, job=args.job,
                        obs_port=args.obs_port))


if __name__ == "__main__":
    main()
