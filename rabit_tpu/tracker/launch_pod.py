"""Multi-host launcher: one worker per TPU-VM host (or hostfile entry).

Equivalent of the reference's cluster launchers
(reference: tracker/rabit_mpi.py:25-40 — mpirun submission;
tracker/rabit_hadoop.py:96-160 — workers as Hadoop streaming mappers).
The TPU-native deployment unit is a pod slice: one worker process per
host, each owning that host's chips, with the tracker reachable over
DCN.  Submission is pluggable the same way the reference's
``fun_submit`` is (reference: tracker/rabit_tracker.py:264-270):

* ``ssh``  — start workers over ssh to each host in a hostfile (the
  classic cluster path; TPU VMs expose plain ssh).
* ``local``— subprocesses on this machine (testing / single host).

The tracker assigns ranks in connect order keyed by task id, so restarts
keep their rank (reference: tracker/rabit_tracker.py:60-65).

Usage:
    python -m rabit_tpu.tracker.launch_pod --hostfile hosts.txt -- \
        python train.py
    python -m rabit_tpu.tracker.launch_pod --local -n 4 -- python train.py
"""
from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
import threading

from rabit_tpu.tracker.tracker import Tracker


def _read_hostfile(path: str) -> list[str]:
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line.split()[0])
    return hosts


def launch_pod(cmd: list[str], hosts: list[str] | None = None,
               n_local: int = 0, tracker_host: str | None = None,
               ssh_opts: str = "", verbose: bool = False) -> int:
    """Run ``cmd`` once per host (or n_local subprocesses).

    Returns 0 when every worker exits cleanly.  Unlike the keepalive
    demo launcher, pod restarts are the platform's job (the reference
    makes the same split: rabit_demo restarts, mpi/hadoop delegate,
    reference: guide/README.md "Fault Tolerance").
    """
    world = len(hosts) if hosts else n_local
    assert world > 0, "no hosts / workers requested"
    # remote workers need a routable tracker address; local ones loopback
    from rabit_tpu.utils.net import routable_ip

    tracker = Tracker(world, host=tracker_host
                      or (routable_ip() if hosts else "127.0.0.1"))
    tracker.start()
    codes: list[int] = [0] * world

    def run_one(i: int) -> None:
        import os

        try:
            env = tracker.worker_env(task_id=str(i))
            if hosts:
                env_prefix = " ".join(
                    f"{k}={shlex.quote(v)}" for k, v in env.items())
                # remote workers mirror the launch cwd (TPU-VM images keep
                # homogeneous paths across a slice)
                remote = (f"cd {shlex.quote(os.getcwd())} && {env_prefix} "
                          + " ".join(shlex.quote(c) for c in cmd))
                full = ["ssh"] + shlex.split(ssh_opts) + [hosts[i], remote]
                if verbose:
                    print(f"[launch_pod] {full}", file=sys.stderr)
                proc = subprocess.Popen(full)
            else:
                penv = dict(os.environ)
                penv.update(env)
                proc = subprocess.Popen(cmd, env=penv)
            codes[i] = proc.wait()
        except Exception as e:  # ssh/worker binary missing, spawn failure
            print(f"[launch_pod] worker {i} failed to start: {e}",
                  file=sys.stderr)
            codes[i] = 1

    threads = [threading.Thread(target=run_one, args=(i,))
               for i in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tracker.join(timeout=10)
    tracker.stop()
    return next((c for c in codes if c != 0), 0)


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="launch rabit_tpu workers across hosts (TPU pod slice)")
    ap.add_argument("--hostfile", help="file with one host per line")
    ap.add_argument("--local", action="store_true",
                    help="run workers as local subprocesses")
    ap.add_argument("-n", "--num-workers", type=int, default=0,
                    help="worker count for --local")
    ap.add_argument("--tracker-host", default=None,
                    help="address workers use to reach the tracker "
                         "(default: this host's primary interface)")
    ap.add_argument("--ssh-opts", default="",
                    help="extra options passed to ssh")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("missing worker command")
    hosts = _read_hostfile(args.hostfile) if args.hostfile else None
    if not hosts and not args.local:
        ap.error("need --hostfile or --local")
    sys.exit(launch_pod(cmd, hosts=hosts, n_local=args.num_workers,
                        tracker_host=args.tracker_host,
                        ssh_opts=args.ssh_opts, verbose=args.verbose))


if __name__ == "__main__":
    main()
