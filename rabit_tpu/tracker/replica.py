"""Directory replication: membership journal + deterministic lease.

ISSUE 19 closes the last control-plane SPOF named by ROADMAP item 4's
follow-ons: one directory process held the fleet's membership, so a
directory death froze registration (shards ride cached snapshots
through an outage, but nothing NEW could join) until an operator
restarted it.  This module holds the two replication primitives the
:class:`~rabit_tpu.tracker.directory.DirectoryServer` composes into a
replica set (doc/fault_tolerance.md "Replicated directory & job
migration"):

* :class:`MembershipJournal` — an append-only JSONL log of membership
  EVENTS (``register`` / ``remove`` / ``takeover``), each stamped with
  the generation it produced.  The leader appends as it mutates its
  :class:`~rabit_tpu.tracker.directory.Directory`; followers mirror
  the log over HTTP (``GET /journal?since=seq``) and fold it into
  their own read-only replica.  On leader takeover the successor
  replays ITS copy — membership survives any single replica's death
  with at most one sync interval of event lag (lost events are only
  liveness beats; the shards' next poll re-registers them).
* :func:`fold_events` — the PURE fold from an event sequence to
  ``(generation, shards)``.  Takeover and replay both go through it,
  and the generation-monotonicity property test drives it over
  recorded sequences: restart, failover and handoff may only move the
  generation FORWARD (a reused generation would un-fence a stale
  leader's cached ring — the double-admission bug).
* :class:`LeaseState` — the deterministic leader lease: the LOWEST
  healthy replica id leads.  There is no vote; each replica probes
  every lower id once per lease interval and leads exactly when all
  of them have missed ``lease_miss`` consecutive probes.  A deposed
  leader (a lower id answers again) steps down on the next probe.
  Generations fence the stale-leader window: a takeover bumps the
  generation past the highest the successor ever OBSERVED, and every
  consumer (shards, clients) adopts snapshots only at monotonically
  non-decreasing generations.
"""
from __future__ import annotations

import json
import os
import threading

from rabit_tpu.utils.checks import log

# Journal event kinds (the complete membership-change vocabulary).
EV_REGISTER = "register"
EV_REMOVE = "remove"
EV_TAKEOVER = "takeover"
EVENT_KINDS = (EV_REGISTER, EV_REMOVE, EV_TAKEOVER)


def fold_events(events) -> tuple[int, dict[int, dict]]:
    """Fold a membership-event sequence into ``(generation, shards)``.

    Pure and total: malformed events are skipped (a torn tail write
    must not poison the replayable prefix), and the generation is the
    MAX seen — replaying any prefix then appending new events can
    therefore never reuse or decrement a generation, which is the
    property the fencing argument (and the property test) rests on."""
    gen = 0
    shards: dict[int, dict] = {}
    for ev in events:
        if not isinstance(ev, dict):
            continue
        kind = ev.get("ev")
        try:
            gen = max(gen, int(ev.get("gen", 0)))
            if kind == EV_REGISTER:
                shards[int(ev["index"])] = {
                    "host": str(ev["host"]), "port": int(ev["port"]),
                    "obs_port": int(ev.get("obs_port", 0))}
            elif kind == EV_REMOVE:
                shards.pop(int(ev["index"]), None)
            elif kind != EV_TAKEOVER:
                continue
        except (KeyError, TypeError, ValueError):
            continue
    return gen, shards


class MembershipJournal:
    """Append-only JSONL membership log, one file per replica.

    Durable (fsync per append — membership events are rare: shards
    joining, dying, leaders taking over; load beats never journal) and
    replayable: a malformed trailing line (torn write at the moment of
    death) is skipped, everything before it folds.  ``path=None``
    keeps the log in memory only (unit tests, ephemeral fleets)."""

    def __init__(self, path: str | None = None) -> None:
        self._path = path
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._seq = 0
        if path and os.path.exists(path):
            self._events = self._read(path)
            self._seq = len(self._events)

    @staticmethod
    def _read(path: str) -> list[dict]:
        out: list[dict] = []
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        log("membership journal %s: skipping malformed "
                            "line (torn tail write?)", path)
                        continue
                    if isinstance(ev, dict):
                        out.append(ev)
        except OSError as e:
            log("membership journal %s unreadable: %s", path, e)
        return out

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def append(self, event: dict) -> dict:
        """Stamp ``event`` with the next sequence number and persist
        it.  A full disk degrades durability (the in-memory log still
        serves followers), never the control plane."""
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq, **event}
            self._events.append(event)
            if self._path:
                try:
                    with open(self._path, "a", encoding="utf-8") as fh:
                        fh.write(json.dumps(event, sort_keys=True) + "\n")
                        fh.flush()
                        os.fsync(fh.fileno())
                except OSError as e:
                    log("membership journal append failed: %s", e)
            return event

    def since(self, seq: int) -> list[dict]:
        """Events with sequence number > ``seq`` (the follower-sync
        wire: each sync round trips only the tail)."""
        with self._lock:
            return [ev for ev in self._events
                    if int(ev.get("seq", 0)) > seq]

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def replay(self) -> tuple[int, dict[int, dict]]:
        """Fold the whole log (leader takeover / replica restart)."""
        return fold_events(self.events())


class LeaseState:
    """Deterministic lowest-healthy-id leader lease for one replica.

    Pure bookkeeping — the owner probes its lower-id peers once per
    lease interval and feeds each verdict in; this class only counts
    consecutive misses and answers :meth:`is_leader`.  Keeping the
    policy separate from the probing makes the failover window
    testable without sockets: leadership moves after exactly
    ``lease_miss`` missed probes (one lease interval's worth), and
    moves BACK the instant a lower id answers again."""

    def __init__(self, replica_index: int, lease_miss: int) -> None:
        self.replica_index = int(replica_index)
        self.lease_miss = max(int(lease_miss), 1)
        self._miss = {i: 0 for i in range(self.replica_index)}
        # The highest generation ever observed from ANY peer: a
        # takeover fences past it, so a stale leader's handed-out
        # generations can never collide with the successor's.
        self.observed_gen = 0

    def probe_result(self, peer: int, alive: bool,
                     generation: int = -1) -> None:
        if peer not in self._miss:
            return
        self._miss[peer] = 0 if alive else self._miss[peer] + 1
        if alive and generation > self.observed_gen:
            self.observed_gen = int(generation)

    def is_leader(self) -> bool:
        """Replica 0 always leads while alive; replica i leads iff
        every lower id has missed its full budget."""
        return all(m >= self.lease_miss for m in self._miss.values())

    def healthy_lower(self) -> list[int]:
        return [i for i, m in sorted(self._miss.items())
                if m < self.lease_miss]

    def dead_lower(self) -> list[int]:
        return [i for i, m in sorted(self._miss.items())
                if m >= self.lease_miss]


def parse_peers(spec: str | None) -> list[str]:
    """Split a ``--peers`` list (comma-separated base URLs, index ==
    replica id) into normalized base URLs."""
    if not spec:
        return []
    out = []
    for part in str(spec).split(","):
        part = part.strip().rstrip("/")
        if not part:
            continue
        if "://" not in part:
            part = "http://" + part
        out.append(part)
    return out
