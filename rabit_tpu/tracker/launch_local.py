"""Local multi-process launcher with keepalive restart.

TPU-native equivalent of the reference's demo launcher
(reference: tracker/rabit_demo.py:28-64): starts a tracker plus N worker
processes, and — the fault-tolerance test harness — restarts any worker
that exits with the kill-point code (254), passing an incremented
``rabit_num_trial`` so deterministic mock kill-points fire once per life.

Usage:
    python -m rabit_tpu.tracker.launch_local -n 4 python guide/basic.py
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading

from rabit_tpu.tracker.tracker import Tracker

# Exit code meaning "killed at a mock kill-point; restart me".  The
# reference uses exit(-2) == 254 (src/allreduce_mock.h:165-171,
# tracker/rabit_demo.py:28-40); we keep the same convention.
RESTART_EXIT_CODE = 254


def launch(n_workers: int, cmd: list[str], max_trials: int = 10,
           verbose: bool = False,
           extra_env: dict[str, str] | None = None) -> int:
    """Run ``cmd`` as n worker processes under a fresh tracker.

    Returns 0 if every worker finished cleanly, else the first non-restart
    non-zero exit code.
    """
    tracker = Tracker(n_workers)
    tracker.start()
    failures: list[int] = []
    live: dict[int, subprocess.Popen] = {}
    lock = threading.Lock()
    aborting = threading.Event()

    def keepalive(worker_id: int) -> None:
        trial = 0
        while not aborting.is_set():
            env = dict(os.environ)
            env.update(extra_env or {})
            env.update(tracker.worker_env(task_id=str(worker_id)))
            env["RABIT_NUM_TRIAL"] = str(trial)
            proc = subprocess.Popen(cmd, env=env)
            with lock:
                live[worker_id] = proc
            code = proc.wait()
            with lock:
                live.pop(worker_id, None)
            if code == RESTART_EXIT_CODE and trial < max_trials:
                trial += 1
                if verbose:
                    print(f"[launch_local] worker {worker_id} hit a "
                          f"kill-point; restart #{trial}", file=sys.stderr)
                continue
            if code != 0 and not aborting.is_set():
                failures.append(code)
                # A permanent failure means the rendezvous barrier can
                # never complete: kill the job instead of letting peers
                # sit in their (up to 600 s) control-plane timeouts.
                aborting.set()
                tracker.stop()
                with lock:
                    for p in live.values():
                        p.terminate()
            return

    threads = [threading.Thread(target=keepalive, args=(i,))
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if not aborting.is_set():
        tracker.join(timeout=10)
    tracker.stop()
    return failures[0] if failures else 0


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="run N rabit_tpu workers locally under a tracker")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--max-trials", type=int, default=10,
                    help="max restarts per worker on kill-point exit (254)")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command and its arguments")
    args = ap.parse_args(argv)
    if args.cmd and args.cmd[0] == "--":  # REMAINDER keeps the separator
        args.cmd = args.cmd[1:]
    if not args.cmd:
        ap.error("missing worker command")
    sys.exit(launch(args.num_workers, args.cmd, args.max_trials, args.verbose))


if __name__ == "__main__":
    main()
