"""Local multi-process launcher with keepalive restart.

TPU-native equivalent of the reference's demo launcher
(reference: tracker/rabit_demo.py:28-64): starts a tracker plus N worker
processes, and — the fault-tolerance test harness — restarts any worker
that exits with the kill-point code (254), passing an incremented
``rabit_num_trial`` so deterministic mock kill-points fire once per life.

Usage:
    python -m rabit_tpu.tracker.launch_local -n 4 python guide/basic.py
"""
from __future__ import annotations

import argparse
import os
import random
import signal
import subprocess
import sys
import threading
import time

from rabit_tpu.tracker.tracker import Tracker

# Exit code meaning "killed at a mock kill-point; restart me".  The
# reference uses exit(-2) == 254 (src/allreduce_mock.h:165-171,
# tracker/rabit_demo.py:28-40); we keep the same convention.
RESTART_EXIT_CODE = 254


def is_dead_exit(code: int, remote: bool = False) -> bool:
    """Did the worker die of a signal (crash/kill/preemption) rather
    than exiting on its own?  The supervisor's restart budget
    (``max_restarts``) covers exactly these: a SIGKILL'd/preempted rank
    is relaunched, while a deliberate non-zero exit (assertion, typed
    error) still aborts the job.  On the ssh leg a remote group kill
    surfaces as 255 (dropped connection) or 128+9."""
    if code < 0:
        return True
    return remote and code in (255, 128 + signal.SIGKILL)


def is_watchdog_exit(code: int, remote: bool = False) -> bool:
    """True when an exit status is one the watchdog's kill can produce.

    The stall killer marks a worker as watchdog-killed *before* the kill
    lands; a worker that crashes on its own in that window must not be
    classified as watchdog-killed, or a genuinely failing worker gets
    silently restarted until the restart budget runs out.  The local
    kill is always SIGKILL (``Popen.kill``); on the ssh leg the local
    client may instead die from the remote group kill reaching it first
    (ssh exits 255 on a dropped connection, or 128+9 when the remote
    shell reports the signal)."""
    if code == -signal.SIGKILL:
        return True
    return remote and code in (255, 128 + signal.SIGKILL)


def restart_delay_ms(nth_restart: int, base_ms: float) -> float:
    """Supervisor relaunch pacing: capped exponential backoff (32x the
    base) with jitter, shared by both launchers."""
    return min(base_ms * (1 << (nth_restart - 1)),
               32.0 * base_ms) * random.uniform(0.5, 1.0)


def make_dead_killer(live: dict, started: dict, lock: threading.Lock,
                     watchdog_killed: set, heartbeat_sec: float | None,
                     label: str, kill_fn=None):
    """Shared heartbeat-verdict policy for the launchers (tracker
    ``on_dead``): kill the declared-dead worker so its keepalive
    restarts it, riding the watchdog-kill bookkeeping (a free restart —
    the launcher caused the death).

    The grace window keeps a stale verdict (the tracker re-notifies
    while a corpse's socket lingers) from killing the freshly
    relaunched life; the tracker re-notifies past it.  ``kill_fn(wid,
    proc)`` overrides the kill transport (the pod launcher kills remote
    workers over ssh) and must guarantee the local ``proc`` dies even
    when the remote leg fails."""
    dead_grace = max(2.0, 3.0 * float(heartbeat_sec or 0.0))

    def on_dead(task_id: str) -> None:
        try:
            wid = int(task_id)
        except (TypeError, ValueError):
            return
        with lock:
            proc = live.get(wid)
            if proc is None or proc.poll() is not None:
                return  # already dead; the keepalive is on it
            if time.monotonic() - started.get(wid, 0.0) < dead_grace:
                return  # freshly (re)started life: not the corpse
            watchdog_killed.add(wid)
        sys.stderr.write(f"[{label}] heartbeat: worker {wid} declared "
                         "dead; killing for restart\n")
        sys.stderr.flush()
        try:
            (kill_fn or (lambda _w, p: p.kill()))(wid, proc)
        except Exception as e:  # noqa: BLE001 — kill transport gone
            sys.stderr.write(f"[{label}] kill of worker {wid} "
                             f"failed: {e}\n")
            sys.stderr.flush()
            proc.kill()  # at minimum the local process must die

    return on_dead


def make_stall_killer(n_workers: int, live: dict, started: dict,
                      lock: threading.Lock, watchdog_killed: set,
                      watchdog_sec: float | None, label: str,
                      kill_fn=None):
    """Shared hung-worker policy for the launchers (tracker ``on_stall``).

    Kills AT MOST ONE hung worker per stall event.  Workers blocked
    inside a device collective (Gloo has no timeout) are unblocked by
    their *peer's* death — killing one sends RSTs that error the others
    out into host-path recovery with their in-memory checkpoint replicas
    intact.  Killing every silent worker at once would destroy all
    replicas and silently restart the job from version 0; if more than
    one is truly wedged, the next stall event (one watchdog period
    later) takes the next one.

    ``kill_fn(wid, proc)`` overrides the kill transport (the pod
    launcher kills remote workers over ssh); it runs OUTSIDE the lock —
    a slow remote kill must not freeze exit bookkeeping — and must
    guarantee the local ``proc`` dies even when the remote leg fails.
    """

    def on_stall(present: set, finished: set) -> None:
        all_ids = {str(i) for i in range(n_workers)}
        for tid in sorted(all_ids - present - finished):
            wid = int(tid)
            with lock:
                proc = live.get(wid)
                if proc is None or proc.poll() is not None:
                    continue  # already dead; keepalive is restarting it
                if (watchdog_sec is not None
                        and time.monotonic() - started.get(wid, 0.0)
                        < watchdog_sec):
                    continue  # freshly (re)started: give it a full period
                watchdog_killed.add(wid)
            sys.stderr.write(f"[{label}] watchdog: worker {wid} is "
                             "hung; killing for restart\n")
            sys.stderr.flush()
            try:
                (kill_fn or (lambda _w, p: p.kill()))(wid, proc)
            except Exception as e:  # noqa: BLE001 — kill transport gone
                sys.stderr.write(f"[{label}] kill of worker {wid} "
                                 f"failed: {e}\n")
                sys.stderr.flush()
                proc.kill()  # at minimum the local process must die
            return

    return on_stall


def launch(n_workers: int, cmd: list[str], max_trials: int = 10,
           verbose: bool = False,
           extra_env: dict[str, str] | None = None,
           watchdog_sec: float | None = None,
           obs_dir: str | None = None,
           max_restarts: int = 0,
           ckpt_dir: str | None = None,
           heartbeat_sec: float | None = None,
           restart_backoff_ms: float = 250.0,
           min_workers: int | None = None,
           max_workers: int | None = None,
           state_dir: str | None = None,
           job: str | None = None,
           obs_port: int | None = None,
           trace_dir: str | None = None) -> int:
    """Run ``cmd`` as n worker processes under a fresh tracker.

    ``job``: name the tenant (``rabit_job_id`` / ``RABIT_JOB_ID``) —
    workers register under this job on the tracker, their structured-
    log lines and obs summaries carry it, and their journal/obs state
    nests under the job's directory.  Mostly useful when several
    launches share one obs/state tree; the in-process tracker here
    serves whatever job its workers bring.

    ``watchdog_sec``: kill + restart workers the tracker reports as hung
    (registered peers are waiting on the rendezvous barrier, this worker
    stayed silent that long).  Detects SIGSTOP'd/wedged workers in
    seconds; safe — a restarted worker reloads from its checkpoint.

    ``obs_dir``: enable the telemetry subsystem — workers dump event
    traces and ship metric summaries there, and the tracker writes the
    aggregated ``obs_report.json`` (doc/observability.md).

    ``obs_port``: serve the live telemetry plane (``GET /metrics``
    Prometheus exposition + ``GET /status`` JSON; ``rabit_top.py``
    polls it) on this port while the job runs — 0 picks an ephemeral
    port (doc/observability.md "Live telemetry").

    ``max_restarts``: the supervisor budget — a worker that dies of a
    signal (SIGKILL, crash, preemption; NOT a deliberate non-zero exit)
    is relaunched up to this many times, paced by capped-exponential
    backoff (``restart_backoff_ms`` base, full jitter).  Combined with
    ``ckpt_dir`` this is the cold-restart path: even killing EVERY rank
    at once resumes the job from the last durably committed version.

    ``ckpt_dir`` / ``heartbeat_sec``: exported to workers as
    ``RABIT_CKPT_DIR`` / ``RABIT_HEARTBEAT_SEC``; a heartbeat period
    also arms the tracker's proactive failure detector, whose dead
    verdicts are handled like watchdog kills (kill + free restart).

    ``min_workers`` / ``max_workers``: **elastic membership**
    (doc/fault_tolerance.md "Elastic membership & tracker HA") — the
    tracker admits late ``cmd=start`` joiners up to the ceiling and
    turns heartbeat-detected deaths into a scale-*down* (never below
    the floor) instead of insisting on a same-rank relaunch; workers
    get ``RABIT_ELASTIC=1`` so the robust engine polls for rescale
    epochs at checkpoint-commit boundaries.  A signal-killed worker
    whose restart budget is spent *leaves* the job (the world shrinks)
    rather than failing it.

    ``state_dir``: journal the tracker's control-plane state through
    the atomic checkpoint-store tier so a restarted tracker on the same
    port resumes the job (the launcher's in-process tracker cannot
    crash alone, but the journal makes the job resumable by a fresh
    launcher pointed at the same state/ckpt dirs, and the standalone
    ``python -m rabit_tpu.tracker.tracker --state-dir`` path is what a
    production supervisor restarts).

    ``trace_dir``: causal-trace/postmortem directory — exported to
    workers as ``RABIT_TRACE_DIR`` so each rank persists its bounded
    flight record there on fault paths (link errors, aborts, SIGTERM),
    and the tracker dumps a control-plane journal at teardown;
    ``tools/postmortem.py`` merges them to reconstruct a dead job's
    last seconds (doc/observability.md "Causal tracing & postmortem").

    Returns 0 if every worker finished cleanly, else the first non-restart
    non-zero exit code.
    """
    if job is not None:
        from rabit_tpu.tracker import protocol as P

        P.require_valid_job_id(job)
    elastic = min_workers is not None or max_workers is not None
    extra_env = dict(extra_env or {})
    if obs_dir is not None:
        extra_env.setdefault("RABIT_OBS_DIR", obs_dir)
    if trace_dir is not None:
        # Workers persist flight records here on fault paths; the
        # tracker writes its control-plane journal at teardown.
        extra_env.setdefault("RABIT_TRACE_DIR", str(trace_dir))
    if ckpt_dir is not None:
        extra_env.setdefault("RABIT_CKPT_DIR", str(ckpt_dir))
    if heartbeat_sec:
        extra_env.setdefault("RABIT_HEARTBEAT_SEC", str(heartbeat_sec))
    if elastic:
        extra_env.setdefault("RABIT_ELASTIC", "1")
    failures: list[int] = []
    live: dict[int, subprocess.Popen] = {}
    lock = threading.Lock()
    aborting = threading.Event()
    watchdog_killed: set[int] = set()

    started: dict[int, float] = {}

    on_stall = make_stall_killer(n_workers, live, started, lock,
                                 watchdog_killed, watchdog_sec,
                                 "launch_local")

    on_dead = make_dead_killer(live, started, lock, watchdog_killed,
                               heartbeat_sec, "launch_local")

    tracker = Tracker(n_workers, watchdog_sec=watchdog_sec,
                      on_stall=on_stall if watchdog_sec else None,
                      obs_dir=obs_dir,
                      on_dead=on_dead if heartbeat_sec else None,
                      min_workers=min_workers, max_workers=max_workers,
                      state_dir=state_dir, obs_port=obs_port,
                      trace_dir=trace_dir)
    tracker.start()

    def keepalive(worker_id: int) -> None:
        trial = 0
        wd_restarts = 0
        sup_restarts = 0
        while not aborting.is_set():
            env = dict(os.environ)
            env.update(extra_env or {})
            env.update(tracker.worker_env(task_id=str(worker_id),
                                          job=job))
            env["RABIT_NUM_TRIAL"] = str(trial)
            # Total restarts of any cause.  Distinct from RABIT_NUM_TRIAL,
            # which counts only kill-point deaths so deterministic mock
            # scenarios stay reproducible under watchdog restarts; the
            # XLA engine keys its mid-job-relaunch (degraded) path on
            # this one.
            env["RABIT_RELAUNCH"] = str(trial + wd_restarts + sup_restarts)
            proc = subprocess.Popen(cmd, env=env)
            with lock:
                live[worker_id] = proc
                started[worker_id] = time.monotonic()
            code = proc.wait()
            with lock:
                live.pop(worker_id, None)
                was_watchdog = worker_id in watchdog_killed
                watchdog_killed.discard(worker_id)
            if (was_watchdog and is_watchdog_exit(code)
                    and wd_restarts < max_trials):
                # same trial number: the worker never reached its
                # kill-point, it was stopped from outside
                wd_restarts += 1
                continue
            if code == RESTART_EXIT_CODE and trial < max_trials:
                trial += 1
                if verbose:
                    sys.stderr.write(
                        f"[launch_local] worker {worker_id} hit a "
                        f"kill-point; restart #{trial}\n")
                continue
            if (is_dead_exit(code) and sup_restarts < max_restarts
                    and not aborting.is_set()):
                # Supervisor path: the worker was killed from outside
                # (preemption, crash, kill-all) — relaunch it under the
                # bounded, backoff-paced restart budget.  Its checkpoint
                # comes back from live replicas or the durable tier.
                sup_restarts += 1
                delay_ms = restart_delay_ms(sup_restarts,
                                            restart_backoff_ms)
                sys.stderr.write(
                    f"[launch_local] supervisor: worker {worker_id} "
                    f"died (exit {code}); relaunch "
                    f"#{sup_restarts}/{max_restarts} in "
                    f"{delay_ms:.0f} ms\n")
                sys.stderr.flush()
                time.sleep(delay_ms / 1000.0)
                continue
            if (elastic and is_dead_exit(code) and not aborting.is_set()):
                # Elastic leave: the restart budget (if any) is spent —
                # a preempted/killed worker departs instead of failing
                # the job.  Tell the tracker directly: with heartbeats
                # armed this is redundant (the EOF verdict fired first),
                # without them it is the ONLY signal that turns the
                # death into a scale-down at the next commit boundary
                # (never below min_workers); if the floor cannot absorb
                # it, the survivors' stall watchdog / link timeouts
                # still bound the job.
                sys.stderr.write(
                    f"[launch_local] elastic: worker {worker_id} left "
                    f"the job (exit {code}); world scales down\n")
                sys.stderr.flush()
                tracker.note_dead(str(worker_id), job=job)
                return
            if code != 0 and not aborting.is_set():
                failures.append(code)
                # A permanent failure means the rendezvous barrier can
                # never complete: kill the job instead of letting peers
                # sit in their (up to 600 s) control-plane timeouts.
                aborting.set()
                tracker.stop()
                with lock:
                    for p in live.values():
                        p.terminate()
            return

    threads = [threading.Thread(target=keepalive, args=(i,))
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if not aborting.is_set():
        tracker.join(timeout=10)
    tracker.stop()
    return failures[0] if failures else 0


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="run N rabit_tpu workers locally under a tracker")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--max-trials", type=int, default=10,
                    help="max restarts per worker on kill-point exit (254)")
    ap.add_argument("--watchdog", type=float, default=None, metavar="SEC",
                    help="kill+restart workers that stall a rendezvous "
                         "round this long (hung-worker detection)")
    ap.add_argument("--obs-dir", default=None,
                    help="enable telemetry: per-rank event traces + the "
                         "tracker-aggregated obs_report.json land here")
    ap.add_argument("--obs-port", type=int, default=None,
                    help="serve the live telemetry plane while the job "
                         "runs: GET /metrics (Prometheus) + GET /status "
                         "(JSON) on this port; 0 = ephemeral "
                         "(doc/observability.md 'Live telemetry')")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="supervisor budget: relaunch a signal-killed "
                         "worker (crash/preemption/kill-all) up to this "
                         "many times, backoff-paced; 0 disables")
    ap.add_argument("--ckpt-dir", default=None,
                    help="durable checkpoint tier: exported to workers "
                         "as RABIT_CKPT_DIR so writer ranks persist "
                         "committed versions and a cold restart resumes "
                         "from disk (doc/fault_tolerance.md)")
    ap.add_argument("--heartbeat", type=float, default=None, metavar="SEC",
                    help="worker keepalive period (RABIT_HEARTBEAT_SEC); "
                         "arms the tracker's proactive failure detector "
                         "— hung ranks are killed+relaunched without a "
                         "collective op having to touch them")
    ap.add_argument("--min-workers", type=int, default=None,
                    help="elastic floor: heartbeat-detected deaths "
                         "scale the world DOWN at the next checkpoint-"
                         "commit boundary (never below this) instead of "
                         "waiting for a same-rank relaunch; enables "
                         "elastic membership (RABIT_ELASTIC=1)")
    ap.add_argument("--max-workers", type=int, default=None,
                    help="elastic ceiling: late cmd=start registrants "
                         "are admitted as joiners at the next rescale "
                         "epoch, up to this world size; enables elastic "
                         "membership (RABIT_ELASTIC=1)")
    ap.add_argument("--state-dir", default=None,
                    help="journal the tracker's control-plane state "
                         "(rank map, epoch, members, barriers) through "
                         "the atomic checkpoint-store tier so a "
                         "restarted tracker resumes the job")
    ap.add_argument("--trace-dir", default=None,
                    help="causal-trace/postmortem directory: exported to "
                         "workers as RABIT_TRACE_DIR so each rank "
                         "persists its crash flight record there on "
                         "fault paths, and the tracker dumps its "
                         "control-plane journal at teardown "
                         "(doc/observability.md 'Causal tracing & "
                         "postmortem')")
    ap.add_argument("--job", default=None, metavar="ID",
                    help="tenant name (rabit_job_id / RABIT_JOB_ID): "
                         "workers register under this job, their log "
                         "lines and obs summaries carry it, and the "
                         "journal/obs state nests per job "
                         "(doc/fault_tolerance.md 'Multi-tenant "
                         "tracker')")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="worker command and its arguments")
    args = ap.parse_args(argv)
    if args.cmd and args.cmd[0] == "--":  # REMAINDER keeps the separator
        args.cmd = args.cmd[1:]
    if not args.cmd:
        ap.error("missing worker command")
    sys.exit(launch(args.num_workers, args.cmd, args.max_trials, args.verbose,
                    watchdog_sec=args.watchdog, obs_dir=args.obs_dir,
                    max_restarts=args.max_restarts, ckpt_dir=args.ckpt_dir,
                    heartbeat_sec=args.heartbeat,
                    min_workers=args.min_workers,
                    max_workers=args.max_workers,
                    state_dir=args.state_dir, job=args.job,
                    obs_port=args.obs_port, trace_dir=args.trace_dir))


if __name__ == "__main__":
    main()
