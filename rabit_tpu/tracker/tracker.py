"""Rendezvous tracker — the control plane, now a multi-tenant service.

TPU-native rebuild of the reference tracker
(reference: tracker/rabit_tracker.py:124-270): assigns ranks (stable per
task_id across restarts), computes the tree+ring topology, hands every
worker its connect/accept lists, relays worker log lines, and terminates
when every job it served has completed.

Design differences from the reference, on purpose:

* Rendezvous is a **full-world barrier**: a round (start or recover)
  completes only when all ``world`` workers have registered, then everyone
  receives a complete topology in one reply.  The reference instead
  incrementally repairs links (src/allreduce_base.cc:207-261); the barrier
  is simpler, and recovery in our robust layer already requires all ranks
  to re-rendezvous (survivors cascade into recovery via link resets).
* Tracker connections are one-shot: each command (start/recover/print/
  shutdown) is a fresh TCP connection, so the tracker holds no long-lived
  per-worker socket state.  The single exception is the heartbeat
  channel (``cmd=heartbeat``): one persistent connection per worker
  carrying periodic keepalives, feeding the deadline-based failure
  detector — liveness is decided proactively on the control plane
  instead of waiting for a collective to error on a corpse
  (doc/fault_tolerance.md "Durable checkpoints & heartbeats").
* The ring is the plain rank cycle and the tree is the binary heap over
  ranks; the reference's DFS edge-sharing optimisation
  (tracker/rabit_tracker.py:167-198) minimises distinct TCP links, which
  stops mattering once bulk data rides ICI/XLA instead of host TCP.
* **Elastic membership** (``min_workers``/``max_workers``): the world
  size is no longer frozen at rendezvous.  A non-member ``cmd=start``
  registrant is admitted as a *joiner* (up to ``max_workers``), a
  heartbeat-detected death becomes a *scale-down* (never below
  ``min_workers``) instead of only a same-rank relaunch, and either
  sets a pending TARGET world.  Members learn about the pending epoch
  at checkpoint-commit boundaries (``cmd=epoch`` polls + the engines'
  K_RESCALE consensus bit) and re-register with ``cmd=rescale``; the
  round completes at the target world, ranks are reassigned
  deterministically (survivors by old rank, then joiners by task_id)
  and the epoch counter in every topology reply is bumped.
* **Restartable control plane** (``state_dir``): the tracker journals
  its state (rank map, epoch, members, committed version, formation
  barrier, liveness timeline) through the atomic
  :class:`~rabit_tpu.ckpt.CheckpointStore` machinery on every mutation.
  A crashed tracker restarted on the same port replays the journal and
  the workers' registration/connect retry bridges the gap — coordinator
  death is a stall, not a job loss (doc/fault_tolerance.md "Elastic
  membership & tracker HA").
* **Multi-tenant service** (doc/fault_tolerance.md "Multi-tenant
  tracker"): every piece of per-job state above lives in a
  :class:`JobState` keyed by the ``job`` field of the worker hello
  (protocol ``MAGIC_JOB``; the classic hello lands in the ``default``
  job, so pre-multi-tenant workers are untouched on the wire).  Jobs
  are created on their first registrant — gated by admission control
  (``--max-jobs`` / ``--max-total-workers``, over-capacity submissions
  get a typed reject reply, re-admitted as soon as a finishing job
  completes) — finish on unanimous goodbye, and an orphan sweep GCs a
  job whose last member vanished without one.  Heartbeat sweeps, EOF
  sweeps, barrier eviction, rescale epochs and journal mutations are
  all job-scoped; obs reports land under ``--obs-dir/<job>/`` and
  journals under ``--state-dir/<job>/`` (the default job keeps the
  pre-tenant root layout), so one tenant's failure storm never touches
  a co-tenant's state.
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import selectors
import socket
import struct
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu import obs
from rabit_tpu.sched import topo as sched_topo
from rabit_tpu.sched import tuner as sched_tuner
from rabit_tpu.tracker import protocol as P
from rabit_tpu.utils.checks import log

DEFAULT_JOB = P.DEFAULT_JOB


def tree_neighbors(rank: int, world: int) -> tuple[int, list[int]]:
    """Binary-heap tree: returns (parent, [parent]+children neighbor list).

    Same shape as the reference's tree map (tracker/rabit_tracker.py:150-166).
    """
    parent = (rank - 1) // 2 if rank > 0 else P.NONE
    neighbors = []
    if rank > 0:
        neighbors.append(parent)
    for child in (2 * rank + 1, 2 * rank + 2):
        if child < world:
            neighbors.append(child)
    return parent, neighbors


def ring_neighbors(rank: int, world: int) -> tuple[int, int]:
    return ((rank - 1) % world, (rank + 1) % world)


@dataclass
class _Registrant:
    sock: socket.socket
    task_id: str
    host: str
    port: int
    cmd: str = P.CMD_START


@dataclass
class _HbPeer:
    """One worker's persistent heartbeat connection (CMD_HEARTBEAT)."""

    sock: socket.socket
    task_id: str
    period_s: float
    last: float                    # monotonic time of the last beat
    buf: bytearray = field(default_factory=bytearray)
    dead: bool = False             # declared dead by the deadline sweep
    bye: bool = False              # clean shutdown seen
    notified: float = 0.0          # last on_dead notification (rearm)
    echo: bool = False             # obs frames seen: echo beats (rtt)
    # Pending echo bytes: a non-blocking send can write PART of a u32,
    # and the worker's echo parser assumes whole-word reads — so
    # unsent tail bytes are buffered and flushed first, never dropped
    # mid-word (a short write must not misalign the echo stream).
    ebuf: bytearray = field(default_factory=bytearray)


class _AdmissionReject(Exception):
    """Internal: a registration failed admission control; the handler
    turns it into the typed wire reject reply."""

    def __init__(self, code: int, kind: str, reason: str) -> None:
        super().__init__(reason)
        self.code = code
        self.kind = kind     # counter suffix: "jobs" | "workers"
        self.reason = reason


class JobState:
    """All control-plane state of ONE job (tenant) served by the
    tracker: rank map, membership, rendezvous barrier, formation
    barrier, heartbeat peers, elastic targets, liveness timeline,
    telemetry aggregation and the durable journal.  Every mutation the
    tracker performs on behalf of a worker is scoped to the worker's
    :class:`JobState` — fault isolation between tenants is structural,
    not policed."""

    def __init__(self, tracker: "Tracker", name: str,
                 n_workers: int) -> None:
        self._tracker = tracker
        self.name = name
        self.n_workers = n_workers
        # Lifecycle: ``touched`` flips on the first admitted worker
        # command (a job exists as a service object only once a worker
        # showed up); ``done`` on unanimous goodbye or orphan GC — a
        # done incarnation holds no capacity and a re-registration
        # under the same name is a NEW job submission.
        self.touched = False
        self.done = False
        self.last_activity = time.monotonic()
        self._rank_of: dict[str, int] = {}      # task_id -> stable rank
        # Tasks that finished (cmd=shutdown).  Keyed by task_id, not
        # rank: elastic rescales reassign ranks, task identity is the
        # stable coordinate.
        self._shutdown_tasks: set[str] = set()
        # Current-epoch membership (task_ids of the last completed
        # round).  Empty until the first round; from then on the job is
        # done when every member has shut down.
        self._members: set[str] = set()
        # Telemetry aggregation (print-channel extension): workers ship
        # rank-local summaries at shutdown (obs.OBS_SUMMARY_PREFIX); the
        # tracker aggregates min/mean/max across ranks into a per-job
        # report under the job's obs dir (doc/observability.md).  The
        # default job keeps the pre-tenant root layout; named jobs nest
        # under ``<obs-dir>/<job>/``.
        self._obs_dir: str | None = None
        self._obs_reports: dict[int, dict] = {}
        self._obs_lock = threading.Lock()
        # Live telemetry plane (doc/observability.md "Live telemetry"):
        # streamed delta frames fold into a per-rank rolling view
        # (journal-free by design) and the shipped collective spans
        # merge into per-op skew + rolling straggler scores.
        self._live = obs.LiveTable()
        self._spans = obs.SpanMerger()
        # Causal trace plane (doc/observability.md "Causal tracing &
        # postmortem"): sampled per-hop records stream in with the
        # frames and assemble into skew-corrected cross-rank timelines,
        # exposed on /trace (Chrome-trace JSON) and as the per-job
        # "trace" section of /status (bound-by verdict, per-link cost
        # table).
        self._traces = obs.TraceAssembler()
        self._straggling: set[int] = set()
        self._obs_frames_bad = 0
        # The job's wire transport and wire codec as reported in its
        # streamed frames (uniform across ranks): both key the
        # controller's online tuner merges (sched/tuner.py table_kind).
        self._transport = "tcp"
        self._codec = "none"
        # Adaptive control plane (obs/adapt.py, tracker --adapt): the
        # per-job controller folds the merged spans into schedule
        # decisions; its directive (payload bucket -> schedule) and
        # straggler-demotion set ride every topology reply and are
        # journaled, so a restarted tracker keeps the job on its
        # learned schedule (the controller's rolling windows rebuild
        # from the live stream).
        self._controller: obs.AdaptiveController | None = None
        self._active_sched: dict[int, str] = {}
        self._demoted: set[int] = set()
        # A controller push pending: the next rendezvous round bumps
        # the epoch at the UNCHANGED world so the whole world adopts
        # the new directive together at a commit boundary.
        self._sched_switch_pending = False
        # True between a controller push and the first tick after its
        # epoch landed — lets the tick re-baseline the probe budget at
        # adoption time, not decision time.
        self._adapt_pushed = False
        self._last_groups: list[int] = []
        # task_ids that completed at least one rendezvous round: a fresh
        # cmd=start from one of these is a mid-job relaunch, flagged in
        # its topology reply (works even when the restarting platform
        # passes a clean environment).
        self._started_tasks: set[str] = set()
        self._pending: list[_Registrant] = []
        self._round_started: float | None = None  # first registrant time
        self._pending_lock = threading.Lock()
        # Keyed coordinator-service ports (cmd=jaxsvc): every worker of
        # THIS job asking for the same key gets the same port; the
        # service objects themselves are tracker-owned (retained until
        # the tracker closes).
        self._jaxsvc_keyed: dict[str, int] = {}
        # Formation barrier (cmd=formbar), one-shot per job: "open" ->
        # "done" (everyone posted) | "aborted" (a relaunch registered, a
        # recover round started, or the barrier timed out).
        self._formbar_state = "open"
        self._formbar_socks: list[socket.socket] = []
        self._formbar_posted: set[str] = set()
        self._formbar_timer: threading.Thread | None = None
        self._formbar_lock = threading.Lock()
        # Heartbeat failure detector state (protocol CMD_HEARTBEAT),
        # job-scoped: task ids are only unique within a job.
        self._hb_peers: dict[str, _HbPeer] = {}
        self._hb_seen: set[str] = set()  # tasks that ever heartbeat —
        # a SECOND channel for the same task is its relaunched life
        self._hb_lock = threading.Lock()
        # Job-scoped liveness/restart timeline (merged into the
        # obs_report recovery timeline next to the workers' events).
        self._events: collections.deque = collections.deque(maxlen=2048)
        # -- elastic membership state ----------------------------------
        self._epoch = 0
        # Pending rescale: the next rendezvous round completes at this
        # world instead of n_workers (None = no rescale pending).
        self._target_world: int | None = None
        self._dead_tasks: set[str] = set()   # members seen dead, unresolved
        self._joiners: set[str] = set()      # parked non-member starts
        # Every task with an unresolved death/loss verdict of ANY kind
        # (heartbeat EOF or deadline, registrant sweep, supervisor
        # note_dead) — cleared by re-registration / a fresh heartbeat
        # channel.  The orphan GC's evidence that the job's members
        # vanished rather than went quiet.
        self._lost_tasks: set[str] = set()
        self._scale_lock = threading.Lock()
        # One thread runs _finish_round at a time (the accept loop on
        # round fill, the heartbeat monitor on a target change).
        self._round_lock = threading.Lock()
        self._committed_version = 0          # max version cmd=epoch reported
        # -- durable control-plane journal (state_dir) -----------------
        self._state_store: ckpt_mod.CheckpointStore | None = None
        self._state_seq = 0
        self._journal_lock = threading.Lock()

    # -- config (tracker-wide knobs, getattr-safe for bare objects) ----
    @property
    def _registrant_timeout(self) -> float:
        return getattr(self._tracker, "_registrant_timeout", 600.0)

    @property
    def _elastic(self) -> bool:
        return getattr(self._tracker, "_elastic", False)

    @property
    def _min_workers(self) -> int | None:
        return getattr(self._tracker, "_min_workers", None)

    @property
    def _max_workers(self) -> int | None:
        return getattr(self._tracker, "_max_workers", None)

    def _tag(self) -> str:
        """Log prefix: the default job keeps the pre-tenant wording."""
        return "" if self.name == DEFAULT_JOB else f" [job {self.name}]"

    # -- lifecycle -----------------------------------------------------
    def job_done(self) -> bool:
        """Job completion.  Before the first round completes the only
        coordinate is the launch count; after it, the job is done when
        every CURRENT member shut down (leavers dropped by a rescale
        owe no goodbye)."""
        if self._members:
            return self._members <= self._shutdown_tasks
        return len(self._shutdown_tasks) >= self.n_workers

    def orphaned(self, now: float) -> str | None:
        """GC predicate for a job whose last member vanished without a
        unanimous goodbye: returns the reason, or None while the job is
        (possibly) alive.  Evidence-based — a job with live heartbeat
        channels, parked registrants, or recent control-plane activity
        is never a candidate, and a job that never armed heartbeats is
        only collected once every member holds an explicit death
        verdict (heartbeat EOF, registrant sweep, supervisor
        note_dead)."""
        if self.done or not self.touched:
            return None
        gc_sec = getattr(self._tracker, "_job_gc_sec", 30.0)
        if now - self.last_activity < gc_sec:
            return None
        with self._pending_lock:
            if self._pending:
                return None
        with self._hb_lock:
            if any(not p.dead for p in self._hb_peers.values()):
                return None
            hb_seen = bool(self._hb_seen)
        if not self._members:
            # Died before the first round ever completed: the only
            # evidence a worker existed at all is a loss verdict (the
            # registrant sweep reaped its parked socket) or a heartbeat
            # life that ended.  Without either, keep waiting — workers
            # may simply not have arrived yet.
            if self._lost_tasks or hb_seen:
                return ("every registrant lost before the first round "
                        "completed")
            return None
        accounted = (self._shutdown_tasks | self._lost_tasks
                     | self._dead_tasks)
        if self._members <= accounted:
            return "every member lost without a unanimous goodbye"
        if hb_seen:
            return (f"heartbeat channels gone and the job idle "
                    f"past {gc_sec:g}s")
        return None

    def close(self) -> None:
        """Drop this job's sockets (pending registrants, heartbeat
        channels) and release its formation barrier."""
        self._abort_formbar("job closing")
        with self._pending_lock:
            for reg in self._pending:
                try:
                    reg.sock.close()
                except OSError:
                    pass
            self._pending.clear()
            self._round_started = None
        with self._hb_lock:
            peers, self._hb_peers = dict(self._hb_peers), {}
        for peer in peers.values():
            try:
                peer.sock.close()
            except OSError:
                pass

    # -- elastic membership + durable journal --------------------------
    def _round_size(self) -> int:
        """How many registrants complete the current rendezvous round:
        the pending rescale target when one is set, else the world."""
        return (self._target_world if self._target_world is not None
                else self.n_workers)

    def _recompute_target(self) -> None:
        """(Re)derive the pending rescale target from membership deltas
        (joiners parked, members dead).  Scale-up needs ``max_workers``,
        scale-down needs ``min_workers`` and never undershoots it; a
        death the floor cannot absorb is left to the supervisor's
        same-rank relaunch path (target cleared).  A changed target
        re-checks round fullness — survivors may already be parked in a
        recover round that the new, smaller target completes."""
        if not self._elastic or not self._members:
            return
        with self._scale_lock:
            alive = self._members - self._dead_tasks
            target = len(alive)
            admitted = 0
            if self._max_workers is not None and self._joiners:
                admitted = min(len(self._joiners),
                               max(self._max_workers - target, 0))
                target += admitted
            if self._dead_tasks:
                if (self._min_workers is None or not alive
                        or target < self._min_workers):
                    target = None  # deaths the elastic floor can't absorb
            elif (target == self.n_workers and not admitted
                    and not self._sched_switch_pending):
                # Nothing changed — unless a controller push is
                # pending, which needs the same-world epoch to land.
                target = None
            changed = target != self._target_world
            self._target_world = target
        if not changed:
            return
        if target is not None:
            log("tracker:%s rescale pending -> world %d (epoch %d -> %d; "
                "%d alive, %d dead, %d joiner(s))", self._tag(), target,
                self._epoch, self._epoch + 1, len(alive),
                len(self._dead_tasks), len(self._joiners))
            self._events.append({
                "ts": time.time(), "name": "epoch", "phase": "pending",
                "epoch": self._epoch + 1, "from_world": self.n_workers,
                "to_world": target})
        self._journal()
        self._maybe_finish_round()

    def _maybe_finish_round(self) -> None:
        """Complete the rendezvous round if the (possibly just-changed)
        target makes the parked registrants a full house."""
        with self._pending_lock:
            full = 0 < self._round_size() <= len(self._pending)
        if full:
            self._finish_round()

    def _journal(self) -> None:
        """Persist the control-plane state through the atomic ckpt-store
        machinery (tmp+fsync+rename, CRC-stamped, bounded retention) so
        a restarted tracker resumes exactly here.  Best-effort: a full
        disk degrades HA, it never kills the running job."""
        if self._state_store is None:
            return
        with self._journal_lock:
            # Snapshot with a bounded retry: the accept, heartbeat and
            # round threads mutate these containers without one global
            # state lock, and iterating a deque/set mid-mutation raises
            # RuntimeError — which must never escape into the serve
            # loop.  A lost race only skips THIS write; the mutation
            # that raced re-journals right behind it.
            for _ in range(3):
                try:
                    state = {
                        "job": self.name,
                        "done": self.done,
                        "epoch": self._epoch,
                        "world": self.n_workers,
                        "rank_of": dict(self._rank_of),
                        "started": sorted(self._started_tasks),
                        "shutdown": sorted(self._shutdown_tasks),
                        "members": sorted(self._members),
                        # Deaths already detected must survive a crash:
                        # a dead worker never reconnects to re-earn its
                        # verdict, so a restart that forgot these would
                        # recompute the target from "everyone alive"
                        # and stall the round on corpses.  _joiners are
                        # deliberately NOT journaled — a parked joiner's
                        # socket died with the old tracker and its
                        # retry re-admits it; a phantom restored joiner
                        # would hold a target slot nothing can fill.
                        "dead": sorted(self._dead_tasks),
                        "lost": sorted(self._lost_tasks),
                        "target_world": self._target_world,
                        "committed_version": self._committed_version,
                        "formbar_state": self._formbar_state,
                        "formbar_posted": sorted(self._formbar_posted),
                        # Adaptive plane: what the controller learned
                        # must survive a tracker crash — a restarted
                        # tracker keeps handing out the learned
                        # directive (its rolling evidence rebuilds
                        # from the live stream).
                        "active_sched": {str(b): s for b, s
                                         in self._active_sched.items()},
                        "demoted": sorted(self._demoted),
                        "events": list(self._events)[-512:],
                    }
                    blob = json.dumps(state, sort_keys=True).encode()
                    break
                except RuntimeError:
                    continue
            else:
                log("tracker:%s state journal snapshot kept racing "
                    "mutations; skipping this write", self._tag())
                return
            self._state_seq += 1
            seq = self._state_seq
            try:
                self._state_store.persist(seq, state["world"], blob)
            except OSError as e:
                log("tracker:%s state journal write failed (seq %d): %s",
                    self._tag(), seq, e)

    def attach_store(self, store: ckpt_mod.CheckpointStore) -> None:
        """Wire this job's journal store; the sequence continues above
        whatever a previous incarnation left on disk."""
        self._state_store = store
        self._state_seq = store.newest_version() or 0

    def restore_journal(self) -> bool:
        """Replay the newest valid journal entry (tracker restart on the
        same port): rank map, epoch, membership, committed version and
        the formation barrier resume where the dead incarnation left
        them; the liveness timeline survives into the next obs report.
        Returns True when a journal was replayed."""
        dc = self._state_store.load_latest()
        if dc is None:
            return False
        try:
            state = json.loads(dc.global_blob.decode())
        except (ValueError, UnicodeDecodeError) as e:
            log("tracker:%s state journal unreadable (%s); starting "
                "fresh", self._tag(), e)
            return False
        self._state_seq = dc.version
        self.done = bool(state.get("done", False))
        self.n_workers = int(state.get("world", self.n_workers))
        self._epoch = int(state.get("epoch", 0))
        self._rank_of = {str(t): int(r)
                         for t, r in state.get("rank_of", {}).items()}
        self._started_tasks = set(state.get("started", []))
        self._shutdown_tasks = set(state.get("shutdown", []))
        self._members = set(state.get("members", []))
        self._dead_tasks = set(state.get("dead", []))
        self._lost_tasks = set(state.get("lost", []))
        tw = state.get("target_world")
        self._target_world = int(tw) if tw is not None else None
        self._committed_version = int(state.get("committed_version", 0))
        self._active_sched = {
            int(b): str(s)
            for b, s in (state.get("active_sched") or {}).items()
            if str(b).lstrip("-").isdigit() and int(b) > 0}
        self._demoted = {int(r) for r in state.get("demoted", [])}
        self._formbar_state = state.get("formbar_state", "open")
        self._formbar_posted = set(state.get("formbar_posted", []))
        if (self._formbar_state == "open"
                and len(self._formbar_posted) >= self.n_workers):
            self._formbar_state = "done"  # resolved mid-crash
        for ev in state.get("events", []):
            self._events.append(ev)
        self._events.append({"ts": time.time(), "name": "tracker",
                             "phase": "restart", "epoch": self._epoch,
                             "world": self.n_workers})
        log("tracker:%s journal replayed (seq %d): world=%d epoch=%d "
            "members=%d committed_version=%d formbar=%s", self._tag(),
            dc.version, self.n_workers, self._epoch, len(self._members),
            self._committed_version, self._formbar_state)
        return True

    # -- formation barrier ---------------------------------------------
    def _formbar_post(self, sock: socket.socket, task_id: str) -> None:
        """See protocol.CMD_FORMBAR.  Parks the socket until the barrier
        resolves; posts after resolution get the resolved answer."""
        with self._formbar_lock:
            if self._formbar_state != "open":
                self._formbar_reply(sock, self._formbar_state == "done")
                return
            self._formbar_socks.append(sock)
            self._formbar_posted.add(task_id)
            if len(self._formbar_posted) >= self.n_workers:
                self._resolve_formbar_locked("done")
                self._journal()
                return
            if self._formbar_timer is None:
                self._formbar_timer = threading.Thread(
                    target=self._formbar_timeout, daemon=True)
                self._formbar_timer.start()
        # Journal each post: a tracker crash mid-barrier must not lose
        # who already arrived — the restarted tracker resumes the round
        # and the (re-)posts of the parked workers complete it.
        self._journal()

    @staticmethod
    def _formbar_reply(sock: socket.socket, proceed: bool) -> None:
        try:
            P.send_u32(sock, 1 if proceed else 0)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _resolve_formbar_locked(self, state: str) -> None:
        self._formbar_state = state
        socks, self._formbar_socks = self._formbar_socks, []
        for s in socks:
            self._formbar_reply(s, state == "done")

    def _abort_formbar(self, why: str) -> None:
        with self._formbar_lock:
            if self._formbar_state == "open" and (
                    self._formbar_socks or self._formbar_posted):
                log("tracker:%s aborting formation barrier (%s)",
                    self._tag(), why)
            if self._formbar_state == "open":
                self._resolve_formbar_locked("aborted")

    def _formbar_timeout(self) -> None:
        deadline = time.monotonic() + self._registrant_timeout
        while time.monotonic() < deadline:
            time.sleep(0.5)
            with self._formbar_lock:
                if self._formbar_state != "open":
                    return
        with self._formbar_lock:
            if self._formbar_state == "open":
                log("tracker:%s formation barrier timed out "
                    "(%d/%d posted); aborting formation", self._tag(),
                    len(self._formbar_posted), self.n_workers)
                self._resolve_formbar_locked("aborted")

    def keyed_jax_service(self, key: str) -> int:
        """Coordinator-service lookup for workers (cmd=jaxsvc).

        ``key == ""``: always a fresh service (device-plane reform needs
        a new incarnation per epoch).  Non-empty key (the engines send
        "init" at job start): create-or-get under one lock — every
        worker of THIS job asks for the same key and receives the SAME
        port, so the init-time coordinator exchange involves no
        worker-to-worker collective at all.  That keeps version-span 0
        free of engine-internal ops: a worker relaunched before the
        first checkpoint replays a span containing only application
        ops, exactly like the survivors'."""
        tr = self._tracker
        with tr._jaxsvc_lock:
            if key and key in self._jaxsvc_keyed:
                return self._jaxsvc_keyed[key]
            port = tr._fresh_jax_service_locked(self.n_workers)
            if key and port:
                self._jaxsvc_keyed[key] = port
            return port

    # -- live telemetry plane ------------------------------------------
    def _obs_frame_ingest(self, task_id: str, raw: bytes) -> None:
        """One streamed obs frame arriving on the heartbeat channel:
        fold the delta metrics into the live table, merge the spans,
        and re-check the straggler verdicts.  Malformed frames are
        counted and dropped — they arrive from the network."""
        try:
            payload = json.loads(raw.decode())
            rank = int(payload["rank"])
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
            self._obs_frames_bad += 1
            log("tracker:%s malformed obs frame from task %r dropped: %s",
                self._tag(), task_id, e)
            return
        self.last_activity = time.monotonic()
        # The job's transport label (uniform across ranks — replicated
        # config + handout): scopes the controller's online tuner
        # merges so shm-measured winners never answer a tcp world.
        transport = payload.get("transport")
        if isinstance(transport, str) and transport:
            self._transport = transport
        # The wire codec label rides the same frames (also replicated
        # config): winners measured over a quantized wire never answer
        # a full-width job, mirroring the transport scoping.
        codec = payload.get("codec")
        if isinstance(codec, str) and codec:
            self._codec = codec
        now = time.time()
        self._live.ingest(rank, now, payload)
        # Clock-skew calibration for the trace plane: the frame carries
        # the sender's wall clock, and its hb-RTT estimate (echoed
        # beats, read time) bounds the flight time — half of it is the
        # classic NTP-style one-way correction.  Folded as a rolling
        # median per rank, so hop timelines from skewed hosts still
        # order causally.
        sent_ts = payload.get("ts")
        if isinstance(sent_ts, (int, float)) and sent_ts > 0:
            rtt = (payload.get("gauges") or {}).get("hb.rtt.seconds.p50")
            rtt = rtt if isinstance(rtt, (int, float)) and rtt > 0 else 0.0
            self._traces.note_offset(rank, now - float(sent_ts) - rtt / 2.0)
        hops = payload.get("hops")
        if hops:
            self._traces.add(rank, hops, self.n_workers)
        spans = payload.get("spans")
        if spans:
            self._spans.add(rank, spans, self.n_workers)
            self._check_stragglers()

    def _check_stragglers(self) -> None:
        """Emit a liveness-style ``straggler`` event when a rank's
        rolling score crosses ``rabit_straggler_factor`` (and a
        recovery event when it falls back under half of it — the
        hysteresis keeps a borderline rank from flapping the
        timeline)."""
        tracker = self._tracker
        factor = getattr(tracker, "_straggler_factor", 3.0)
        min_sec = getattr(tracker, "_straggler_min_sec", 0.05)
        verdicts = self._spans.straggler_verdicts(factor, min_sec)
        current = {r for r, _s, _l in verdicts}
        for rank, score, late in verdicts:
            if rank in self._straggling:
                continue
            self._straggling.add(rank)
            log("tracker:%s rank %d is STRAGGLING: mean lateness "
                "%.1f ms = %.1fx the op cost (factor %g)", self._tag(),
                rank, late * 1e3, score, factor)
            self._events.append({
                "ts": time.time(), "name": "straggler",
                "phase": "straggler", "rank": rank,
                "score": round(score, 2),
                "lateness_sec": round(late, 4), "factor": factor})
            tracker._count("job.stragglers")
        for rank in sorted(self._straggling - current):
            if self._spans.score(rank) < factor / 2:
                self._straggling.discard(rank)
                log("tracker:%s rank %d recovered from straggling",
                    self._tag(), rank)
                self._events.append({
                    "ts": time.time(), "name": "straggler",
                    "phase": "recovered", "rank": rank})

    # -- adaptive control plane (obs/adapt.py) -------------------------
    def _adapt_tick(self) -> None:
        """One controller pass for this job (tracker --adapt sweep):
        fold the merged spans into a schedule/demotion verdict and push
        any decision as a schedule-switch epoch.  Skipped while a
        rescale is already in flight — one pending epoch at a time
        keeps the round bookkeeping trivial."""
        tracker = self._tracker
        if not self._members or self.n_workers < 2:
            return
        ctl = self._controller
        if ctl is None or ctl.world != self.n_workers \
                or ctl.groups != self._last_groups:
            # (Re)built on first use and after every membership change:
            # the candidate set and demotion streaks belong to ONE
            # (world, topology); learned directives persist in
            # _active_sched and the TuningCache.
            if ctl is not None:
                # An actual world/groups CHANGE: timings, lateness and
                # straggler evidence measured at the old world (old
                # rank numbering!) must not feed the new one's
                # decisions or cache merges.
                self._spans.reset_windows()
                self._straggling.clear()
            ctl = self._controller = obs.AdaptiveController(
                self.n_workers, self._last_groups,
                straggler_factor=getattr(tracker, "_straggler_factor",
                                         3.0))
            # Demotions outside the new rank space are meaningless (a
            # shrink renumbered the world); in-range ones carry over
            # and self-heal via the controller's no-signal
            # reinstatement if the rank's straggling didn't.
            self._demoted = {r for r in self._demoted
                             if r < self.n_workers}
            ctl.demoted = set(self._demoted)
            ctl.active = dict(self._active_sched)
            # settled holds PLAIN schedule names (the scorer's
            # incumbent domain); a journaled slashed ``sched/codec``
            # directive value seeds only its schedule half — the codec
            # suffix is re-derived from live evidence each tick.
            ctl.settled = {b: s.split("/", 1)[0]
                           for b, s in self._active_sched.items()}
        with self._scale_lock:
            if self._target_world is not None:
                return  # an epoch is already pending; decide after it
        if self._adapt_pushed:
            # The pushed epoch completed since the last tick (target is
            # clear again): the workers adopted the directive only NOW,
            # so the probe's abandonment budget starts here.
            self._adapt_pushed = False
            ctl.note_epoch_landed(self._spans.merged_ops)
        # wire=self._codec: schedule evidence is scoped to spans that
        # actually rode the job's codec wire — full-width opt-out ops
        # never steer the verdicts merged under codec-keyed rows.
        actions = ctl.tick(self._spans, self._spans.scores(),
                           wire=getattr(self, "_codec", "none"))
        if not actions:
            return
        for act in actions:
            self._apply_controller_action(ctl, act)
        self._active_sched = dict(ctl.active)
        self._demoted = set(ctl.demoted)
        if any(a.kind in ("probe", "switch", "settle", "demote",
                          "reinstate", "codec") for a in actions):
            self._adapt_pushed = True
            self._push_sched_epoch()
        self._journal()

    def _apply_controller_action(self, ctl, act) -> None:
        """Record one controller decision: timeline event (with the
        evidence), service counter, structured log — and, for final
        schedule verdicts, the online TuningCache merge that makes the
        next job start warm."""
        tracker = self._tracker
        # Liveness-style past-tense phases on the timeline (the
        # decision KIND keeps the imperative form for counters/soak).
        phase = {"demote": "demoted",
                 "reinstate": "reinstated"}.get(act.kind, act.kind)
        ev = {"ts": act.ts, "name": "controller", "phase": phase}
        if act.bucket is not None:
            ev["bucket"] = act.bucket
        if act.sched is not None:
            ev["sched"] = act.sched
        if act.rank is not None:
            ev["rank"] = act.rank
        evd = act.evidence or {}
        for k in ("incumbent", "incumbent_sec", "challenger_sec",
                  "score", "factor", "why",
                  # codec-override decisions (RABIT_ADAPT_CODEC)
                  "base_sec", "codec_sec", "codec"):
            if k in evd:
                ev[k] = evd[k]
        self._events.append(ev)
        tracker._count(f"controller.decisions.{act.kind}")
        if act.kind == "switch":
            log("tracker:%s controller SWITCH %dB -> %s (incumbent %s "
                "%.3fms vs challenger %.3fms over %s samples)",
                self._tag(), act.bucket or 0, act.sched,
                evd.get("incumbent"),
                float(evd.get("incumbent_sec", 0)) * 1e3,
                float(evd.get("challenger_sec", 0)) * 1e3,
                evd.get("samples"))
        elif act.kind == "demote":
            log("tracker:%s controller DEMOTED rank %d from leader "
                "roles (straggler score %s > factor %s)", self._tag(),
                act.rank, evd.get("score"), evd.get("factor"))
        elif act.kind == "reinstate":
            log("tracker:%s controller REINSTATED rank %d (score %s)",
                self._tag(), act.rank, evd.get("score"))
        else:
            log("tracker:%s controller %s %s", self._tag(), act.kind,
                act.sched or act.rank)
        if act.kind in ("switch", "settle") and act.bucket is not None:
            merge = getattr(tracker, "_tune_merge", None)
            if merge is not None:  # bare test objects lack the cache
                merge("allreduce", self.n_workers, act.bucket, act.sched,
                      getattr(self, "_transport", "tcp"),
                      getattr(self, "_codec", "none"))

    def _push_sched_epoch(self) -> None:
        """Arm a schedule-switch epoch: the next rendezvous round
        completes at the UNCHANGED world with a bumped epoch, so every
        member adopts the new directive/demotion set together at its
        next commit boundary (the K_RESCALE consensus — PR 6's rescale
        choreography reused verbatim)."""
        with self._scale_lock:
            self._sched_switch_pending = True
            if self._target_world is None:
                self._target_world = len(self._members) or self.n_workers
        # No journal here: the only caller (_adapt_tick) journals right
        # after applying the whole action batch — one atomic write per
        # decision, not two back-to-back.
        self._maybe_finish_round()

    # -- telemetry aggregation -----------------------------------------
    def _obs_ingest(self, raw: str) -> None:
        """One rank's shutdown summary arriving on the print channel.
        Summaries for the same rank merge section-wise: a layered engine
        ships two (the XLA engine's device-plane instruments plus its
        host inner's — disjoint metric names), and within one section
        the newest shipment wins per name (a relaunched worker's final
        life supersedes; only lives that reach shutdown ship at all)."""
        try:
            payload = json.loads(raw)
            rank = int(payload["rank"])
        except (ValueError, KeyError, TypeError) as e:
            log("tracker:%s malformed obs summary dropped: %s",
                self._tag(), e)
            return
        with self._obs_lock:
            have = self._obs_reports.get(rank)
            if have is None:
                self._obs_reports[rank] = payload
                return
            for section, vals in payload.get("metrics", {}).items():
                have.setdefault("metrics", {}).setdefault(
                    section, {}).update(vals)
            have.setdefault("recovery", []).extend(
                payload.get("recovery", []))
            have["engine"] = payload.get("engine", have.get("engine"))

    def _write_obs_report(self) -> None:
        """Aggregate the shipped rank summaries into the per-job report
        (min/mean/max across ranks + a merged recovery timeline; the
        tracker's own liveness/restart transitions land on the same
        timeline, ts-sorted next to the recovery phases they caused).
        Lands under this JOB's obs dir — co-tenant reports never
        collide."""
        with self._obs_lock:
            reports = dict(self._obs_reports)
        tracker_events = list(self._events)
        if not self._obs_dir or not (reports or tracker_events):
            return
        timeline = list(tracker_events)
        for rank, rep in reports.items():
            for ev in rep.get("recovery", []):
                ev = dict(ev)
                ev.setdefault("rank", rank)
                timeline.append(ev)
        timeline.sort(key=lambda e: e.get("ts", 0.0))
        report = {
            "job": self.name,
            "world": self.n_workers,
            "ranks_reported": sorted(reports),
            "ranks": {str(r): rep for r, rep in sorted(reports.items())},
            "aggregate": obs.aggregate_snapshots(
                [rep.get("metrics", {}) for rep in reports.values()]),
            "recovery_timeline": timeline,
            "service": self._tracker._service_report(),
        }
        # Sharded control plane: a job hosted by a ShardServer stamps
        # its shard index so a fleet-collected report stays attributable
        # after the files leave the shard's obs dir.
        shard = getattr(self._tracker, "_shard_index", None)
        if shard is not None:
            report["shard"] = shard
        # Live-plane sections (streaming export + merged spans): the
        # straggler table and per-schedule latency/skew breakdown the
        # obs_report renderer turns into tables.
        span_rep = self._spans.report()
        if span_rep["merged_ops"]:
            report["straggler"] = {
                "ranks": span_rep["ranks"],
                "straggling": sorted(self._straggling),
                "factor": getattr(self._tracker,
                                  "_straggler_factor", 3.0),
            }
            report["sched_latency"] = span_rep["sched"]
        # Adaptive-controller section: the decisions with their
        # evidence, the directive the job converged on and the
        # demotion set (rendered by obs_report as the decision table).
        if self._controller is not None or self._active_sched \
                or self._demoted:
            ctl = self._controller
            report["controller"] = {
                "active_sched": {str(b): s for b, s
                                 in sorted(self._active_sched.items())},
                "demoted": sorted(self._demoted),
                "decisions": ([d.as_dict() for d in ctl.decisions]
                              if ctl is not None else []),
                "counters": (dict(ctl.counters)
                             if ctl is not None else {}),
            }
        live = self._live.report()
        if live:
            report["live"] = {"ranks": live,
                              "frames_bad": self._obs_frames_bad}
        try:
            os.makedirs(self._obs_dir, exist_ok=True)
            path = os.path.join(self._obs_dir, "obs_report.json")
            with open(path, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
            log("tracker:%s wrote obs report (%d ranks) to %s",
                self._tag(), len(reports), path)
        except OSError as e:
            log("tracker:%s obs report write failed: %s", self._tag(), e)

    # -- liveness / heartbeat ------------------------------------------
    def _emit_liveness(self, phase: str, task_id: str, **fields) -> None:
        """One control-plane liveness transition (alive / dead / lost /
        relaunch) for the merged obs timeline."""
        ev = {"ts": time.time(), "name": "liveness", "phase": phase,
              "task": task_id}
        rank = self._rank_of.get(task_id)
        if rank is not None:
            ev["rank"] = rank
        for k, v in fields.items():
            if v is not None:
                ev[k] = v
        self._events.append(ev)

    def note_dead(self, task_id: str) -> None:
        """Supervisor-facing death notice: the launcher's keepalive saw
        the worker process exit and will not relaunch it (elastic
        leave).  Redundant when the heartbeat channel is armed — its
        EOF verdict fires first and ``_note_dead`` dedups — but the
        ONLY death signal the tracker gets in elastic mode without
        heartbeats.  Liveness first, so the timeline orders the loss
        ahead of the scale-down it triggers."""
        self._lost_tasks.add(task_id)
        if not self._elastic or task_id in self._dead_tasks:
            return
        self._emit_liveness("lost", task_id, supervisor=1)
        self._evict_registrant(task_id, "supervisor reported it dead")
        self._note_dead(task_id)

    def _note_dead(self, task_id: str) -> None:
        """Elastic-mode death bookkeeping: a member the heartbeat layer
        saw die (EOF without the goodbye, or a missed-beat verdict) is
        marked dead and the rescale target recomputed — scale-down
        instead of waiting for a same-rank relaunch.  Callers emit the
        liveness transition FIRST, so the timeline orders the death
        ahead of the epoch move it causes."""
        if not self._elastic or task_id not in self._members:
            return
        if task_id in self._dead_tasks:
            return
        self._dead_tasks.add(task_id)
        self._recompute_target()

    def _hb_register(self, sock: socket.socket, task_id: str,
                     period_ms: int) -> None:
        """A worker opened its persistent heartbeat channel; a fresh
        connection for a known task is its relaunched life."""
        sock.setblocking(False)
        peer = _HbPeer(sock, task_id, max(int(period_ms), 1) / 1000.0,
                       time.monotonic())
        with self._hb_lock:
            old = self._hb_peers.pop(task_id, None)
            relaunched = old is not None or task_id in self._hb_seen
            self._hb_seen.add(task_id)
            self._hb_peers[task_id] = peer
        if old is not None:
            try:
                old.sock.close()
            except OSError:
                pass
        log("tracker:%s heartbeat channel open for task %r "
            "(period %d ms%s)", self._tag(), task_id, period_ms,
            ", relaunched" if relaunched else "")
        self._emit_liveness("alive", task_id,
                            relaunched=1 if relaunched else None)
        self._lost_tasks.discard(task_id)
        if self._elastic and task_id in self._dead_tasks:
            # Back from the dead (relaunch beat the scale-down): the
            # pending target stops counting it out.
            self._dead_tasks.discard(task_id)
            self._recompute_target()

    def _hb_forget(self, peer: _HbPeer) -> None:
        with self._hb_lock:
            if self._hb_peers.get(peer.task_id) is peer:
                del self._hb_peers[peer.task_id]
        try:
            peer.sock.close()
        except OSError:
            pass

    def _hb_drain(self, peer: _HbPeer, now: float) -> None:
        """Consume whatever beats arrived on one heartbeat socket."""
        tracker = self._tracker
        try:
            data = peer.sock.recv(4096)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            # EOF/RST without the bye: the process died.  The launcher
            # watches the process directly, so no on_dead escalation —
            # but the parked registrant (if any) must still go, and the
            # transition belongs in the timeline.
            # No registrant eviction here: the dead process's parked
            # rendezvous socket EOFs too and the registrant sweep reaps
            # it, while a late-drained EOF must never close a freshly
            # relaunched life's registrant parked under the same task.
            self._hb_forget(peer)
            if not peer.bye and not peer.dead and not tracker._stopped:
                log("tracker:%s heartbeat channel for task %r lost (EOF)",
                    self._tag(), peer.task_id)
                self._emit_liveness("lost", peer.task_id)
                self._lost_tasks.add(peer.task_id)
                # Elastic mode: a SIGKILL'd/preempted worker EOFs its
                # channel instantly and never earns a deadline verdict —
                # this IS the death signal that triggers scale-down.
                self._note_dead(peer.task_id)
            return
        peer.buf += data
        while len(peer.buf) >= 4:
            (beat,) = struct.unpack_from("<I", peer.buf)
            if beat == P.HEARTBEAT_OBS:
                # Telemetry frame multiplexed onto the beat stream:
                # sentinel, u32 length, JSON payload.  Incomplete
                # frames wait in peer.buf for the next drain.
                if len(peer.buf) < 8:
                    break
                (ln,) = struct.unpack_from("<I", peer.buf, 4)
                if ln > P.MAX_PRINT_LEN:
                    log("tracker:%s oversized obs frame (%d bytes) from "
                        "task %r; dropping the heartbeat channel",
                        self._tag(), ln, peer.task_id)
                    self._hb_forget(peer)
                    return
                if len(peer.buf) < 8 + ln:
                    break
                raw = bytes(peer.buf[8:8 + ln])
                del peer.buf[:8 + ln]
                peer.last = now   # a frame proves liveness like a beat
                peer.echo = True  # an obs worker reads echoes (hb.rtt)
                self._obs_frame_ingest(peer.task_id, raw)
                continue
            del peer.buf[:4]
            if beat == P.HEARTBEAT_BYE:
                peer.bye = True
                self._hb_forget(peer)
                self._emit_liveness("shutdown", peer.task_id)
                return
            peer.last = now
            if peer.echo:
                # Echo the beat back so the worker can measure its
                # heartbeat round trip (hb.rtt.seconds).  Best-effort:
                # a backed-up socket drops WHOLE echoes (bounded
                # pending buffer), while a short write keeps its tail
                # buffered so the worker's u32 parser never misaligns.
                if len(peer.ebuf) <= 60:  # cap: 16 pending echoes
                    peer.ebuf += struct.pack("<I", beat)
                try:
                    sent = peer.sock.send(peer.ebuf)
                    del peer.ebuf[:sent]
                except (BlockingIOError, InterruptedError):
                    pass
                except OSError:
                    peer.ebuf.clear()  # channel dying; EOF path owns it
            if peer.dead:
                # Beats resumed after a dead verdict (a SIGCONT'd rank
                # the supervisor has not reaped yet): record the flap;
                # the supervisor's kill remains in flight.
                peer.dead = False
                log("tracker:%s task %r resumed heartbeats after a dead "
                    "verdict", self._tag(), peer.task_id)
                self._emit_liveness("alive", peer.task_id, resumed=1)
                self._lost_tasks.discard(peer.task_id)
                if self._elastic and peer.task_id in self._dead_tasks:
                    # The scale-down verdict is withdrawn: the rank is
                    # demonstrably alive on the SAME channel (no
                    # relaunch happened), so it keeps its membership
                    # instead of staying permanently counted out.
                    self._dead_tasks.discard(peer.task_id)
                    self._recompute_target()

    def _hb_mark_dead(self, peer: _HbPeer, phase: str, why: str) -> None:
        """Deadline verdict: evict the corpse from the barrier and tell
        the supervisor.  Re-notifies every miss budget while the verdict
        stands, so a supervisor that skipped a kill (restart grace) gets
        another chance instead of the job wedging."""
        tracker = self._tracker
        renotify = max(peer.period_s * tracker._hb_miss, 0.5)
        now = time.monotonic()
        if peer.dead and now - peer.notified < renotify:
            return
        first = not peer.dead
        peer.dead = True
        peer.notified = now
        if first:
            log("tracker:%s task %r declared dead by the heartbeat sweep "
                "(%s)", self._tag(), peer.task_id, why)
            self._emit_liveness(phase, peer.task_id, why=why)
            self._lost_tasks.add(peer.task_id)
            # Evict only on the FIRST verdict: no EOF means the hung
            # process is still alive holding its sockets, so a parked
            # registrant is provably the hung life's own.  A re-notify
            # runs after the supervisor's kill — by then the task's
            # NEXT life may already be parked, and closing its socket
            # would abort the very relaunch the kill arranged.
            self._evict_registrant(peer.task_id, why)
            # Elastic mode: the liveness verdict above precedes this —
            # scale-down is its consequence on the timeline.
            self._note_dead(peer.task_id)
        if tracker._on_dead is not None:
            try:
                tracker._on_dead(peer.task_id)
            except Exception as e:  # noqa: BLE001 — detector must survive
                log("tracker:%s on_dead callback failed: %s",
                    self._tag(), e)

    def _evict_registrant(self, task_id: str, why: str) -> None:
        """Drop a dead task's PARKED rendezvous registrant so the round
        re-opens (the hung-but-connected sibling of the EOF-based
        registrant sweep: a SIGSTOP'd rank keeps its sockets open, so
        only the heartbeat verdict can evict it)."""
        with self._pending_lock:
            if len(self._pending) >= self._round_size():
                return  # full round: the reply loop owns these sockets
            lost = [r for r in self._pending if r.task_id == task_id]
            if not lost:
                return
            self._pending = [r for r in self._pending
                             if r.task_id != task_id]
            if not self._pending:
                self._round_started = None
        for reg in lost:
            log("tracker:%s evicted registrant task %r from the "
                "rendezvous barrier (%s); the round re-opens for its "
                "relaunch", self._tag(), reg.task_id, why)
            try:
                reg.sock.close()
            except OSError:
                pass

    def sweep_registrants_once(self) -> None:
        """One pass of the dead-registrant sweep: drop EOF'd parked
        registrants so a partially-filled round re-opens instead of
        wedging the survivors (see Tracker._sweep_registrants)."""
        with self._pending_lock:
            if (not self._pending
                    or len(self._pending) >= self._round_size()):
                return
            socks = [r.sock for r in self._pending]
        # selectors (epoll/poll), not select.select: fds above
        # FD_SETSIZE would make select raise on every pass and
        # silently disable the sweep for big/long-lived jobs.
        sel = selectors.DefaultSelector()
        try:
            for s in socks:
                try:
                    sel.register(s, selectors.EVENT_READ)
                except (OSError, ValueError):
                    continue  # closed under us; next sweep re-checks
            ready = [key.fileobj for key, _ in sel.select(0)]
        finally:
            sel.close()
        dead = set()
        for s in ready:
            try:
                if s.recv(1, socket.MSG_PEEK) == b"":
                    dead.add(s)
            except OSError:
                dead.add(s)
        if not dead:
            return
        with self._pending_lock:
            if len(self._pending) >= self._round_size():
                return  # round filled meanwhile: let it reply
            lost = [r for r in self._pending if r.sock in dead]
            self._pending = [r for r in self._pending
                             if r.sock not in dead]
            if not self._pending:
                self._round_started = None
        for reg in lost:
            log("tracker:%s registrant task %r (cmd=%s) lost during "
                "the rendezvous barrier; dropping it and re-opening "
                "the round (its restart will re-register)",
                self._tag(), reg.task_id, reg.cmd)
            # Liveness BEFORE any membership/topology consequence:
            # the obs timeline must order the loss causally ahead of
            # the rescale/round it triggers.
            self._emit_liveness("lost", reg.task_id, barrier=1)
            self._lost_tasks.add(reg.task_id)
            try:
                reg.sock.close()
            except OSError:
                pass
            if self._elastic:
                if reg.task_id in self._joiners:
                    # A joiner that died while parked stops holding
                    # a slot in the pending target.
                    self._joiners.discard(reg.task_id)
                    self._recompute_target()
                elif reg.task_id in self._members:
                    self._note_dead(reg.task_id)

    # -- rendezvous ----------------------------------------------------
    def register(self, sock: socket.socket, cmd: str, task_id: str,
                 host: str, port: int) -> None:
        """Park one start/recover/rescale registrant in this job's
        rendezvous barrier (and complete the round if it fills)."""
        self.last_activity = time.monotonic()
        self._lost_tasks.discard(task_id)
        # Any recover/rescale round, or a fresh start from a task
        # that already ran, means the membership moved: an open
        # formation barrier can never complete — release it as
        # aborted so no survivor walks into the doomed device-group
        # registration.
        if cmd != P.CMD_START or task_id in self._started_tasks:
            self._abort_formbar("task %r re-registered (cmd=%s)"
                                % (task_id, cmd))
            if cmd == P.CMD_START:
                # A mid-job relaunch re-registering: a restart event
                # for the merged liveness timeline.
                self._emit_liveness("relaunch", task_id)
        # Registered: the socket now waits on the barrier, not on a
        # half-read message — lift the handshake timeout.
        sock.settimeout(self._registrant_timeout)
        # A re-registration from the same task replaces its stale entry
        # (e.g. worker crashed after registering, restarted mid-round).
        with self._pending_lock:
            stale = [r for r in self._pending if r.task_id == task_id]
            for r in stale:
                try:
                    r.sock.close()
                except OSError:
                    pass
            self._pending = [r for r in self._pending
                             if r.task_id != task_id]
            if not self._pending:
                self._round_started = time.monotonic()
            self._pending.append(
                _Registrant(sock, task_id, host, port, cmd))
        if self._elastic:
            if task_id in self._dead_tasks:
                # A presumed-dead member registered — ANY cmd proves
                # life (a supervisor relaunch's fresh start, or a
                # live member whose abandoned registration socket
                # the sweep mistook for a death retrying its
                # recover/rescale) — so it must not stay counted
                # out of the pending target.
                self._dead_tasks.discard(task_id)
                self._recompute_target()
            elif (cmd == P.CMD_START
                    and self._members and task_id not in self._members
                    and self._max_workers is not None):
                # Late joiner: parks until a rescale round admits it.
                if task_id not in self._joiners:
                    self._joiners.add(task_id)
                    self._emit_liveness("join_request", task_id)
                    self._recompute_target()
        self._maybe_finish_round()

    def _assign_ranks(self, regs: list[_Registrant] | None = None) -> None:
        # Shuffle the free-rank pool before handing ranks to NEW task
        # ids (the reference shuffles its todo_nodes for load balance,
        # tracker/rabit_tracker.py:242): arrival order otherwise
        # correlates host startup speed with tree position, piling the
        # root's traffic onto whatever machine booted first.  Restarted
        # tasks keep their old rank regardless (stable-rank contract).
        # RABIT_TRACKER_SHUFFLE=0 restores plain arrival order
        # (deterministic rank <-> arrival mapping for debugging).
        #
        # RABIT_TRACKER_PIN_RANKS=1: a task_id that is a decimal integer
        # in [0, n_workers) CLAIMS that rank.  This is the mixed-mode
        # alignment knob (doc/scaling.md): when an external runtime
        # already fixed each process's jax.process_index(), the engine
        # registers with task_id = that index, and pinning makes the
        # control-plane rank equal to it — the XLA engine requires the
        # two numberings to agree before it will use the device plane.
        import random

        if regs is None:
            regs = self._pending
        used = set(self._rank_of.values())
        if os.environ.get("RABIT_TRACKER_PIN_RANKS", "0") in (
                "1", "true", "yes"):
            for reg in regs:
                tid = reg.task_id
                if tid not in self._rank_of and tid.isdecimal():
                    r = int(tid)
                    if r < self.n_workers and r not in used:
                        self._rank_of[tid] = r
                        used.add(r)
        free = [r for r in range(self.n_workers) if r not in used]
        if os.environ.get("RABIT_TRACKER_SHUFFLE", "1") not in (
                "0", "false", "no"):
            random.shuffle(free)
        it = iter(free)
        for reg in regs:
            if reg.task_id not in self._rank_of:
                self._rank_of[reg.task_id] = next(it)

    def _assign_ranks_rescale(self, regs: list[_Registrant],
                              world: int) -> None:
        """Deterministic rank reassignment for a rescale round:
        surviving members keep their relative (old-rank) order — a pure
        scale-up moves nobody — and joiners follow, sorted by task_id,
        compacting the rank space to exactly ``[0, world)``."""
        old = sorted((r for r in regs if r.task_id in self._rank_of),
                     key=lambda r: self._rank_of[r.task_id])
        new = sorted((r for r in regs if r.task_id not in self._rank_of),
                     key=lambda r: r.task_id)
        self._rank_of = {reg.task_id: i for i, reg in enumerate(old + new)}
        assert len(self._rank_of) == world

    def _select_round_locked(self, world: int
                             ) -> tuple[list[_Registrant],
                                        list[_Registrant]]:
        """Pick which parked registrants form this round (caller holds
        ``_pending_lock``).  Normally everyone; when MORE are parked
        than the round admits (joiners beyond ``max_workers``), members
        and already-ranked tasks go first, then joiners by task_id —
        the extras stay parked for a later epoch."""
        pending = list(self._pending)
        if len(pending) <= world:
            return pending, []
        core = [r for r in pending
                if not self._members or r.task_id in self._members
                or r.task_id in self._rank_of]
        rest = sorted((r for r in pending if r not in core),
                      key=lambda r: r.task_id)
        chosen = (core + rest)[:world]
        chosen_ids = {id(r) for r in chosen}
        extras = [r for r in pending if id(r) not in chosen_ids]
        return chosen, extras

    def _topo_groups(self, by_rank: dict, world: int) -> list[int]:
        """Host-group handout for the topology-aware schedules: one
        group id per rank.  Ranks whose registrants advertised the same
        host share an id (the ``launch_pod`` shape the hierarchical
        schedule keys off); ``RABIT_TRACKER_GROUPS`` ("0,0,1,1" by
        rank) overrides for tests and explicit pinning.  Ids are dense
        in first-seen rank order, so the handout is deterministic for a
        given rank map — a recover round reproduces it exactly."""
        raw = os.environ.get("RABIT_TRACKER_GROUPS", "").strip()
        if raw:
            try:
                ids = [int(x) for x in raw.replace(";", ",").split(",")
                       if x.strip() != ""]
            except ValueError:
                ids = []
            # Ids travel as wire u32s: range-check here so a bad
            # override is ignored with a log line instead of a
            # struct.error mid-handout (which would strand the ranks
            # not yet replied to).
            if len(ids) == world and all(0 <= g < (1 << 32)
                                         for g in ids):
                return ids
            log("tracker: RABIT_TRACKER_GROUPS %r invalid for world %d "
                "(need %d comma-separated u32 ids); ignoring",
                raw, world, world)
        seen: dict[str, int] = {}
        return [seen.setdefault(by_rank[rank].host, len(seen))
                for rank in range(world)]

    def _finish_round(self) -> None:
        """All workers registered: compute topology, reply to everyone.

        A worker dying between registering and its reply must not wedge the
        tracker: its send failure drops only that registrant (it will
        re-register on restart) while every other socket is still replied
        to and closed.  Survivors that already got a topology naming the
        dead worker will fail link setup and come back with cmd=recover.

        When a rescale target is pending the round IS the rescale: it
        completed at the target world, so membership, ranks and the
        epoch move here — liveness events for the deaths/joins that
        caused it were already emitted by the heartbeat sweep and the
        admission path, so the timeline orders cause before effect.
        """
        with self._round_lock:
            # One consistent read of the pending target decides BOTH
            # the round size and whether this round is a rescale: a
            # concurrent _recompute_target (e.g. a presumed-dead member
            # re-registering) must not make them disagree and ship a
            # topology whose world and rank space come from different
            # targets.  A target that changes after this read simply
            # opens the next round (_recompute_target re-derives it
            # from the completed round's membership below).
            with self._scale_lock:
                target = self._target_world
            rescale = target is not None
            world = target if rescale else self.n_workers
            with self._pending_lock:
                if not 0 < world <= len(self._pending):
                    return  # raced: another thread already served it
                regs, extras = self._select_round_locked(world)
                self._pending = extras
                self._round_started = (time.monotonic() if extras
                                       else None)
            if rescale:
                old_world, old_epoch = self.n_workers, self._epoch
                self._assign_ranks_rescale(regs, world)
                self.n_workers = world
                self._epoch += 1
                members = {r.task_id for r in regs}
                with self._scale_lock:
                    self._target_world = None
                    self._sched_switch_pending = False
                    self._dead_tasks &= members
                    self._lost_tasks &= members
                    self._joiners -= members
                log("tracker:%s rescale complete — world %d -> %d, epoch "
                    "%d -> %d (%d member(s))", self._tag(), old_world,
                    world, old_epoch, self._epoch, len(members))
                self._events.append({
                    "ts": time.time(), "name": "epoch", "phase": "rescale",
                    "epoch": self._epoch, "from_world": old_world,
                    "to_world": world})
            else:
                self._assign_ranks(regs)
                members = {r.task_id for r in regs}
            by_rank = {self._rank_of[r.task_id]: r for r in regs}
            addr = {rk: (reg.host, reg.port) for rk, reg in by_rank.items()}
            groups = self._topo_groups(by_rank, world)
            self._last_groups = groups  # the controller's topology view
            # Adaptive handout: demotions only make sense inside the
            # current rank space; the directive string rides verbatim.
            demoted = sorted(r for r in self._demoted if r < world)
            directive = sched_tuner.encode_directive(self._active_sched)
            for rank, reg in sorted(by_rank.items()):
                parent, neighbors = tree_neighbors(rank, world)
                rp, rn = ring_neighbors(rank, world)
                # Beyond the tree/ring links, wire every peer the
                # topology-aware schedules can ask for (halving/doubling
                # XOR partners, Swing hops, hierarchical leader links) —
                # O(log world) extras per rank, computed from the SAME
                # functions the engine-side applies() checks consult
                # (rabit_tpu/sched/topo.py), so a schedule never meets a
                # missing link at dispatch time.
                extra = sched_topo.extra_link_peers(rank, world, groups,
                                                    demoted)
                linkset = sorted(set(neighbors + list(extra)
                                     + ([rp, rn] if world > 1 else [])))
                linkset = [r for r in linkset if r != rank]
                # Deterministic direction: connect to lower ranks,
                # accept higher.
                connect = [(r, addr[r][0], addr[r][1])
                           for r in linkset if r < rank]
                naccept = sum(1 for r in linkset if r > rank)
                relaunched = int(reg.cmd == P.CMD_START
                                 and reg.task_id in self._started_tasks)
                reply = P.TopologyReply(
                    rank=rank, world=world, parent=parent,
                    neighbors=neighbors, ring_prev=rp, ring_next=rn,
                    connect=connect, naccept=naccept,
                    relaunched=relaunched, epoch=self._epoch,
                    groups=groups, sched=directive, demoted=demoted)
                try:
                    reply.send(reg.sock)
                    # Mark "completed a round" only on a delivered
                    # reply: a worker that died before receiving its
                    # first topology never ran with it, so its restart
                    # is a fresh start, not a mid-job relaunch.
                    self._started_tasks.add(reg.task_id)
                except OSError as e:
                    log("tracker:%s worker rank %d died before its "
                        "reply: %s", self._tag(), rank, e)
                try:
                    reg.sock.close()
                except OSError:
                    pass
            self._members = members
            self._journal()
        # Registrants still parked after ANY completed round open the
        # next epoch's target: joiners beyond max_workers, joiners that
        # arrived before the FIRST round completed (membership was
        # empty, so the admission branch could not see them), and
        # members a concurrent target change dropped from this round.
        self._admit_parked()

    def _admit_parked(self) -> None:
        """Sweep the still-parked registrants into the joiner set and
        re-derive the pending rescale target.  Runs after every
        completed round — without it, a cmd=start that raced the round
        it missed would sit parked until its registration socket times
        out instead of being admitted at the next commit boundary."""
        if not self._elastic:
            return
        if self._max_workers is not None:
            # cmd=start: ordinary late joiners.  cmd=rescale from a
            # NON-member: a worker a concurrent target change dropped
            # from the round it re-registered for — it rejoins at the
            # next epoch rather than stalling out its parked socket.
            with self._pending_lock:
                parked = [r.task_id for r in self._pending
                          if r.cmd in (P.CMD_START, P.CMD_RESCALE)
                          and r.task_id not in self._members]
            fresh = [t for t in parked if t not in self._joiners]
            for tid in fresh:
                self._joiners.add(tid)
                self._emit_liveness("join_request", tid)
        self._recompute_target()


class Tracker:
    """Accepts worker connections and serves rendezvous rounds — for
    one job (the embedded launcher shape) or many concurrent jobs (the
    standalone multi-tenant service)."""

    def __init__(self, n_workers: int, host: str = "127.0.0.1", port: int = 0,
                 watchdog_sec: float | None = None,
                 on_stall: Optional[Callable[[set, set], None]] = None,
                 registrant_timeout_sec: float | None = None,
                 obs_dir: str | None = None,
                 heartbeat_miss: float | None = None,
                 on_dead: Optional[Callable[[str], None]] = None,
                 min_workers: int | None = None,
                 max_workers: int | None = None,
                 state_dir: str | None = None,
                 max_jobs: int | None = None,
                 max_total_workers: int | None = None,
                 job_gc_sec: float | None = None,
                 obs_port: int | None = None,
                 straggler_factor: float | None = None,
                 adapt: bool = False,
                 tune_dir: str | None = None,
                 trace_dir: str | None = None):
        """``n_workers`` is the DEFAULT job's world size (and the world
        assumed for a named job whose first registrant sent no world
        hint).

        ``watchdog_sec``: if a rendezvous round stays *partially*
        registered this long, the tracker calls ``on_stall(present_task_
        ids, finished_task_ids)`` so the launcher can kill/restart the
        silent workers — a hung (SIGSTOP'd, wedged) rank is then replaced
        in seconds instead of holding the barrier for the full link
        timeout (reference analogue: the tracker-side liveness the
        reference delegates to its job manager).

        ``heartbeat_miss`` / ``on_dead``: the proactive heartbeat
        failure detector.  Workers launched with ``rabit_heartbeat_sec``
        keep one persistent CMD_HEARTBEAT connection each; a worker
        whose beats stop for ``heartbeat_miss`` periods (default 3, env
        ``RABIT_HEARTBEAT_MISS``) is declared dead: its parked
        rendezvous registrant (if any) is evicted so the round
        re-opens, the liveness transition lands in the obs timeline,
        and ``on_dead(task_id)`` tells the supervisor to kill/relaunch
        it — all without any collective op having to touch the corpse
        first.

        ``min_workers`` / ``max_workers``: enable **elastic
        membership** (per job).  With ``max_workers`` set, late
        ``cmd=start`` registrants beyond a job's current membership are
        admitted as joiners (pending rescale epoch at the next commit
        boundary); with ``min_workers`` set, a worker whose death the
        heartbeat channel reveals triggers a scale-*down* rescale
        instead of waiting for a same-rank relaunch — never below the
        floor.  Leaving both ``None`` freezes each job's world at its
        registration size exactly as before.

        ``state_dir``: journal the control-plane state through the
        atomic CheckpointStore tier so a restarted tracker (same port)
        resumes every in-flight job.  The default job journals at the
        ``state_dir`` root (the pre-multi-tenant layout); named jobs
        journal under ``state_dir/<job>/``, and a restart replays ALL
        of them.

        ``max_jobs`` / ``max_total_workers``: **admission control** for
        the multi-tenant service.  A registration that would create a
        job past either bound gets a typed reject reply (protocol
        ``REJECT_MAX_JOBS`` / ``REJECT_MAX_WORKERS``) instead of
        parking forever; capacity is released the moment a job
        finishes (or is orphan-GC'd), so a rejected submission's
        backoff retry is admitted as soon as a finishing job drains —
        not held off for its whole retry budget.  ``None`` = unbounded.

        ``job_gc_sec`` (env ``RABIT_JOB_GC_SEC``, default 30): how long
        a job must sit idle — no parked registrants, no live heartbeat
        channels, every member holding a death verdict or goodbye —
        before the orphan sweep garbage-collects it.

        ``obs_port``: serve the **live telemetry plane** over HTTP on
        this port (0 = ephemeral; the bound port lands in
        ``self.obs_port``): ``GET /metrics`` is the Prometheus text
        exposition (labels ``job``/``rank``/``sched``), ``GET /status``
        the per-job JSON state — members, epoch, committed version,
        liveness, straggler scores (doc/observability.md "Live
        telemetry"; ``tools/rabit_top.py`` polls it).  None disables.

        ``straggler_factor`` (env ``RABIT_STRAGGLER_FACTOR``, default
        3): a rank whose rolling mean lateness across merged collective
        spans exceeds this many op-times (and the
        ``RABIT_STRAGGLER_MIN_SEC`` absolute floor, default 0.05 s)
        gets a ``straggler`` event on the job timeline.

        ``adapt``: arm the **adaptive controller** (doc/performance.md
        "Online adaptation"): per job, the merged-span fold is
        re-scored online and schedule switches / straggler demotions
        are pushed as schedule-switch epochs at the workers' commit
        boundaries (workers must run ``rabit_adapt=1`` to poll for
        them).  ``tune_dir``: load-or-create a :class:`TuningCache`
        there and atomically re-persist what the controller learns, so
        the next ``rabit_sched=auto`` job starts warm."""
        self._default_world = n_workers
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(256)
        self.host, self.port = self._listener.getsockname()
        self._obs_base = obs_dir if obs_dir is not None \
            else os.environ.get("RABIT_OBS_DIR") or None
        self._thread: threading.Thread | None = None
        self._stopped = False
        self._watchdog_sec = watchdog_sec
        self._on_stall = on_stall
        # socket timeout applied to registered rendezvous sockets: it
        # bounds the tracker's blocking SENDS when a round completes (a
        # wedged worker cannot hold _finish_round's reply loop), not the
        # barrier wait itself — a partially-filled round is bounded by
        # the stall watchdog (watchdog_sec), and the workers' own link
        # timeouts bound their side.  Defaults to the job's configured
        # RABIT_TIMEOUT_SEC instead of a hardcoded 600 s.
        if registrant_timeout_sec is None:
            try:
                registrant_timeout_sec = float(
                    os.environ.get("RABIT_TIMEOUT_SEC", 600))
            except ValueError:
                registrant_timeout_sec = 600.0
        self._registrant_timeout = max(float(registrant_timeout_sec), 1.0)
        # tracker-hosted JAX coordination services (cmd=jaxsvc).  Old
        # epochs' services are RETAINED until the tracker closes: a
        # degraded member whose disconnect RPC failed can still have an
        # error-polling thread attached to an old service, and killing
        # that service fatally terminates the member (client.h:80's
        # default callback).  One retained service per re-formation,
        # bounded by the job's failure count.  The service objects are
        # tracker-owned; the keyed create-or-get maps are per job.
        self._jaxsvcs: list = []
        self._jaxsvc_lock = threading.Lock()
        # Heartbeat failure detector config (protocol CMD_HEARTBEAT).
        if heartbeat_miss is None:
            try:
                heartbeat_miss = float(
                    os.environ.get("RABIT_HEARTBEAT_MISS", 3))
            except ValueError:
                heartbeat_miss = 3.0
        self._hb_miss = max(float(heartbeat_miss), 1.0)
        self._on_dead = on_dead
        # -- elastic membership config (applies to every job) ----------
        self._min_workers = min_workers
        self._max_workers = max_workers
        self._elastic = min_workers is not None or max_workers is not None
        # -- multi-tenant service state --------------------------------
        self._max_jobs = max_jobs
        self._max_total_workers = max_total_workers
        if job_gc_sec is None:
            try:
                job_gc_sec = float(os.environ.get("RABIT_JOB_GC_SEC", 30))
            except ValueError:
                job_gc_sec = 30.0
        self._job_gc_sec = max(float(job_gc_sec), 0.5)
        self._svc_lock = threading.Lock()
        self._svc_counters: collections.Counter = collections.Counter()
        self._jobs_touched = 0     # jobs that ever admitted a worker
        # Admission linger: a submission rejected at capacity is
        # re-polling with backoff right now — the service must not shut
        # down between the finishing job that freed the slot and the
        # rejected worker's next retry, or "admitted once the finishing
        # job completes" silently becomes "connection refused".
        self._last_reject: float | None = None
        # The jobs dict may already exist: the legacy-alias path
        # (attribute access on a bare object) lazily creates it.
        self.__dict__.setdefault("_jobs", {})
        self.__dict__.setdefault("_jobs_lock", threading.Lock())
        # -- durable control-plane journal (state_dir) -----------------
        self._state_base = str(state_dir) if state_dir else None
        default = self._default_job()
        default.n_workers = n_workers
        default._obs_dir = self._obs_base
        if self._state_base:
            default.attach_store(ckpt_mod.CheckpointStore(
                self._state_base, rank=0, keep=3))
            if default.restore_journal():
                self._mark_restored(default)
            self._restore_named_jobs()
        # -- live telemetry exposition (obs_port) ----------------------
        if straggler_factor is None:
            try:
                straggler_factor = float(
                    os.environ.get("RABIT_STRAGGLER_FACTOR", 3.0))
            except ValueError:
                straggler_factor = 3.0
        self._straggler_factor = max(float(straggler_factor), 1.0)
        try:
            self._straggler_min_sec = float(
                os.environ.get("RABIT_STRAGGLER_MIN_SEC", 0.05))
        except ValueError:
            self._straggler_min_sec = 0.05
        # Serving SLO target for the burn-rate exposition rows
        # (doc/observability.md "Serving SLO").
        try:
            self._serve_slo_target = float(
                os.environ.get("RABIT_SERVE_SLO_TARGET", 0.99))
        except ValueError:
            self._serve_slo_target = 0.99
        # Postmortem directory (--trace-dir): the tracker dumps each
        # job's control-plane journal (liveness/recovery timeline +
        # assembled trace summary) there at teardown, next to the
        # workers' flight records (workers persist theirs via
        # RABIT_TRACE_DIR — launch_local --trace-dir sets both).
        self._trace_dir = str(trace_dir) if trace_dir else None
        self._obs_server = None
        self.obs_port: int | None = None
        if obs_port is not None:
            self._start_obs_server(obs_port)
        # -- adaptive controller (obs/adapt.py) ------------------------
        self._adapt = bool(adapt)
        self._tune_dir = str(tune_dir) if tune_dir else None
        self._tune_lock = threading.Lock()
        self._tuning_cache: sched_tuner.TuningCache | None = None
        if self._tune_dir:
            self._tuning_cache = (
                sched_tuner.TuningCache.load(self._tune_dir)
                or sched_tuner.TuningCache({}, {"host": self.host,
                                               "source": "online"}))
        if self._adapt:
            if not self._tune_dir:
                log("tracker: --adapt without --tune-dir: decisions "
                    "apply live but are not persisted for future jobs")
            threading.Thread(target=self._adapt_loop,
                             daemon=True).start()
        if watchdog_sec is not None and on_stall is not None:
            threading.Thread(target=self._watchdog, daemon=True).start()
        # Registrant-loss sweep: a worker that dies while PARKED in the
        # rendezvous barrier must not keep holding a slot (see
        # JobState.sweep_registrants_once); the same cadence runs job
        # completion/orphan GC.
        threading.Thread(target=self._sweep_registrants,
                         daemon=True).start()
        threading.Thread(target=self._hb_monitor, daemon=True).start()

    # -- job registry --------------------------------------------------
    def _default_job(self) -> JobState:
        """The default tenant's JobState, created lazily so the legacy
        single-job attribute surface (``tracker._pending`` & co, used
        by tests and tools) keeps working — including on bare
        ``Tracker.__new__`` objects that unit tests assemble by hand."""
        jobs = self.__dict__.get("_jobs")
        if jobs is None:
            jobs = {}
            self.__dict__["_jobs"] = jobs
            self.__dict__.setdefault("_jobs_lock", threading.Lock())
        job = jobs.get(DEFAULT_JOB)
        if job is None:
            job = JobState(self, DEFAULT_JOB,
                           self.__dict__.get("_default_world", 0))
            jobs[DEFAULT_JOB] = job
        return job

    def _job_list(self) -> list[JobState]:
        with self._jobs_lock:
            return list(self._jobs.values())

    def _active_jobs(self) -> list[JobState]:
        with self._jobs_lock:
            return [j for j in self._jobs.values()
                    if j.touched and not j.done]

    def _live_jobs(self) -> list[JobState]:
        """Jobs the background sweeps must watch: everything not done.
        Deliberately wider than :meth:`_active_jobs` — a heartbeat
        channel (or a parked registrant) can exist before the job's
        first registration is admitted."""
        with self._jobs_lock:
            return [j for j in self._jobs.values() if not j.done]

    def _job_get(self, name: str) -> JobState | None:
        """The current live incarnation of a job, or None (unknown or
        already finished)."""
        with self._jobs_lock:
            job = self._jobs.get(name)
        return None if job is None or job.done else job

    def _mark_restored(self, job: JobState) -> None:
        """A journal replayed at startup: the job is mid-flight (it
        only has a journal because workers registered) and holds
        capacity again."""
        if not job.touched:
            job.touched = True
            self._jobs_touched += 1
        self._count("job.restored")

    def _restore_named_jobs(self) -> None:
        """Replay every named job's journal under ``state_dir/<job>/``
        (the default job's lives at the root).  Finished jobs are left
        on disk but not resurrected."""
        try:
            names = sorted(os.listdir(self._state_base))
        except OSError:
            return
        for name in names:
            sub = os.path.join(self._state_base, name)
            if (name == DEFAULT_JOB or not P.valid_job_id(name)
                    or not os.path.isdir(sub)):
                continue
            job = JobState(self, name, self._default_world)
            if self._obs_base:
                job._obs_dir = os.path.join(self._obs_base, name)
            try:
                job.attach_store(ckpt_mod.CheckpointStore(
                    sub, rank=0, keep=3))
            except OSError as e:
                log("tracker: cannot open job %r journal under %s: %s",
                    name, sub, e)
                continue
            if job.restore_journal() and not job.done:
                with self._jobs_lock:
                    self._jobs[name] = job
                self._mark_restored(job)

    def _check_capacity_locked(self, name: str, world: int) -> None:
        """Admission bounds for one NEW job of ``world`` ranks (caller
        holds ``_jobs_lock``).  Raises :class:`_AdmissionReject` — and
        by contract no state may have been created for the job yet, so
        a rejected submission leaves nothing behind (no JobState to
        sweep forever, no state_dir/<job>/ on disk)."""
        active = [j for j in self._jobs.values()
                  if j.touched and not j.done]
        if self._max_jobs is not None and len(active) >= self._max_jobs:
            raise _AdmissionReject(
                P.REJECT_MAX_JOBS, "jobs",
                f"job {name!r} refused: {len(active)} active "
                f"job(s) at the --max-jobs={self._max_jobs} "
                "capacity; retry after one finishes")
        if self._max_total_workers is not None:
            total = sum(j.n_workers for j in active)
            if total + world > self._max_total_workers:
                raise _AdmissionReject(
                    P.REJECT_MAX_WORKERS, "workers",
                    f"job {name!r} refused: {total} worker(s) "
                    f"active + {world} requested exceeds "
                    f"--max-total-workers={self._max_total_workers}"
                    "; retry after one finishes")

    def _admitted_locked(self, job: JobState) -> None:
        """Capacity charged: lifecycle bookkeeping for a job that just
        admitted its first worker (caller holds ``_jobs_lock``)."""
        job.touched = True
        self._jobs_touched += 1
        self._count("job.created")
        job._events.append({
            "ts": time.time(), "name": "job", "phase": "created",
            "job": job.name, "world": job.n_workers})
        log("tracker: job %r admitted (world %d; %d job(s) active)",
            job.name, job.n_workers,
            sum(1 for j in self._jobs.values()
                if j.touched and not j.done))

    def _admit(self, name: str, world_hint: int) -> JobState:
        """Resolve a registration's job, creating (and admission-
        checking) a fresh incarnation when none is live.  Capacity is
        charged when a job first admits a worker and released the
        moment it finishes, so a rejected submission's backoff retry
        lands as soon as a finishing job drains.  Raises
        :class:`_AdmissionReject` for the typed wire reply — BEFORE any
        job state is created, so rejects cannot accumulate zombie
        JobStates or journal directories."""
        fresh = False
        with self._jobs_lock:
            job = self._jobs.get(name)
            if job is not None and job.done:
                job = None
            if job is not None:
                if not job.touched:
                    # The pre-created default job (legacy alias
                    # surface): charge admission on its first worker.
                    self._check_capacity_locked(name, job.n_workers)
                    self._admitted_locked(job)
                return job
            # A named job's world comes from its first registrant's
            # hint; the default job (and hint-less registrants) use
            # the tracker's configured world.  Admission runs before
            # the JobState exists.
            world = (world_hint if world_hint > 0
                     and name != DEFAULT_JOB else self._default_world)
            self._check_capacity_locked(name, world)
            job = JobState(self, name, world)
            if self._obs_base:
                job._obs_dir = (self._obs_base if name == DEFAULT_JOB
                                else os.path.join(self._obs_base, name))
            self._jobs[name] = job
            self._admitted_locked(job)
            fresh = True
        if fresh and self._state_base:
            # Journal store creation does disk I/O (makedirs, stale-tmp
            # sweep): done OUTSIDE _jobs_lock so one tenant's slow
            # storage cannot stall every co-tenant's command dispatch
            # and heartbeat sweep.  Only _handle's accept thread admits
            # jobs, so nobody races the late attach; journal writes
            # before it simply skip (best-effort by contract).
            sub = (self._state_base if name == DEFAULT_JOB
                   else os.path.join(self._state_base, name))
            try:
                job.attach_store(ckpt_mod.CheckpointStore(
                    sub, rank=0, keep=3))
            except OSError as e:
                log("tracker: job %r journal unavailable (%s); "
                    "running without HA for it", name, e)
        return job

    def _finish_job(self, job: JobState, phase: str) -> None:
        """Complete a job's lifecycle (unanimous goodbye or orphan GC):
        release its capacity, drop its sockets, write its obs report,
        journal the terminal state, and wake the serve loop if it was
        the last one."""
        with self._jobs_lock:
            if job.done:
                return
            job.done = True
        log("tracker:%s job %s (%d member(s), %d shutdown)",
            job._tag() or " [job default]", phase, len(job._members),
            len(job._shutdown_tasks))
        job._events.append({"ts": time.time(), "name": "job",
                            "phase": phase, "job": job.name,
                            "world": job.n_workers})
        self._count("job.finished" if phase == "finished"
                    else "job.orphan_gc")
        job.close()
        job._write_obs_report()
        job._journal()
        if self._service_done():
            self._wake_accept()

    def _count(self, name: str, n: int = 1) -> None:
        """Service-level ``job.*`` counters (admissions, completions,
        GCs, dropped strays) — stamped into every per-job obs report's
        ``service`` section."""
        with self._svc_lock:
            self._svc_counters[name] += n

    def _service_report(self) -> dict:
        with self._jobs_lock:
            active = sorted(j.name for j in self._jobs.values()
                            if j.touched and not j.done)
        with self._svc_lock:
            counters = dict(self._svc_counters)
        return {"jobs_active": active, "counters": counters}

    # How long the service outlives its last job while a rejected
    # submission may still be re-polling admission (see _last_reject).
    # Must cover one worker-side backoff step after the LAST reject:
    # pysocket caps the step at 32 x rabit_backoff_base_ms, so the
    # default covers bases up to ~900 ms; deployments with slower
    # backoff bases raise it via RABIT_ADMISSION_LINGER_SEC.
    ADMISSION_LINGER_SEC = 30.0

    def _service_done(self) -> bool:
        """Serve-loop exit condition: at least one job ever admitted a
        worker, every admitted job has finished, and no capacity-
        rejected submission is plausibly still re-polling.  (A tracker
        that never saw a worker keeps waiting — same as before.)"""
        with self._jobs_lock:
            if self._jobs_touched == 0:
                return False
            if not all(j.done for j in self._jobs.values() if j.touched):
                return False
        try:
            linger = float(os.environ.get("RABIT_ADMISSION_LINGER_SEC",
                                          self.ADMISSION_LINGER_SEC))
        except ValueError:
            linger = self.ADMISSION_LINGER_SEC
        return (self._last_reject is None
                or time.monotonic() - self._last_reject >= linger)

    def _wake_accept(self) -> None:
        """Nudge the accept loop so it re-checks the exit condition —
        job completion can happen on a sweep thread while run() is
        blocked in accept()."""
        host = self.host if self.host not in ("0.0.0.0", "::") \
            else "127.0.0.1"
        try:
            socket.create_connection((host, self.port), timeout=2).close()
        except OSError:
            pass

    # -- public --------------------------------------------------------
    @property
    def uri(self) -> str:
        return self.host

    def worker_env(self, task_id: str,
                   job: str | None = None) -> dict[str, str]:
        """Environment for a worker process launched under this tracker.
        ``job`` names the tenant (default: the default job — byte-
        compatible with pre-multi-tenant workers)."""
        world = self.n_workers
        env = {
            "RABIT_TRACKER_URI": self.host,
            "RABIT_TRACKER_PORT": str(self.port),
            "RABIT_TASK_ID": str(task_id),
        }
        if job and job != DEFAULT_JOB:
            env["RABIT_JOB_ID"] = str(job)
            j = self._job_get(str(job))
            if j is not None:
                world = j.n_workers
        env["RABIT_WORLD_SIZE"] = str(world)
        return env

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def join(self, timeout: float | None = None) -> None:
        assert self._thread is not None
        self._thread.join(timeout)

    def run(self) -> None:
        """Serve until every admitted job has completed (or stop() is
        called)."""
        while not self._service_done() and not self._stopped:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                break
            # Bound the handshake so one silent client can't stall the
            # whole control plane; barrier waits happen after _handle.
            sock.settimeout(30)
            try:
                self._handle(sock)
            except (ConnectionError, OSError) as e:
                # A worker dying mid-handshake is survivable: drop it from
                # the pending barrier; it will re-register on restart.
                log("tracker: dropped connection during handshake: %s", e)
                for job in self._job_list():
                    with job._pending_lock:
                        job._pending = [r for r in job._pending
                                        if r.sock is not sock]
                try:
                    sock.close()
                except OSError:
                    pass
        self._close_all()

    def stop(self) -> None:
        """Abort the tracker (e.g. the launcher saw a permanent worker
        failure).  Pending workers get connection resets and fail fast
        instead of sitting in the rendezvous barrier."""
        self._stopped = True
        try:
            # Unblock accept() by closing the listener.
            self._listener.close()
        except OSError:
            pass

    # -- legacy single-job surface (the default tenant) ----------------
    @property
    def epoch(self) -> int:
        """Default job's membership epoch (bumped per completed rescale
        round)."""
        return self._default_job()._epoch

    @property
    def committed_version(self) -> int:
        """Max checkpoint version any default-job worker reported via
        cmd=epoch."""
        return self._default_job()._committed_version

    def _job_done(self) -> bool:
        return self._default_job().job_done()

    def note_dead(self, task_id: str, job: str | None = None) -> None:
        """Supervisor-facing death notice (see JobState.note_dead).
        ``job`` names the tenant (None = the default job)."""
        j = self._job_get(job or DEFAULT_JOB)
        if j is not None:
            j.note_dead(task_id)

    def _obs_ingest(self, raw: str) -> None:
        self._default_job()._obs_ingest(raw)

    def _write_obs_report(self) -> None:
        self._default_job()._write_obs_report()

    def _assign_ranks(self, regs: list[_Registrant] | None = None) -> None:
        self._default_job()._assign_ranks(regs)

    def _assign_ranks_rescale(self, regs: list[_Registrant],
                              world: int) -> None:
        self._default_job()._assign_ranks_rescale(regs, world)

    # -- service internals ---------------------------------------------
    def _fresh_jax_service_locked(self, world: int) -> int:
        """Host a fresh JAX coordination service for one job's world;
        returns its port (0 if jaxlib isn't importable or no port could
        be bound).  Caller holds ``_jaxsvc_lock``.

        The jaxlib service object has no port accessor, so binding it to
        port 0 is useless — a free port is probed first.  The probe binds
        the SAME wildcard namespace the service will use (IPv6 any,
        falling back to IPv4 any on IPv6-less hosts), and the residual
        probe-close -> service-bind race is handled by retrying with a
        fresh port instead of failing the job over to the
        rank-0-hosted path."""
        try:
            from jax._src.lib import _jax as jaxlib_ext
        except Exception as e:  # noqa: BLE001
            log("tracker: cannot host jax coordination service: %s", e)
            return 0
        last: Exception | None = None
        for _ in range(5):
            try:
                probe = socket.socket(socket.AF_INET6,
                                      socket.SOCK_STREAM)
                try:
                    probe.bind(("::", 0))
                except OSError:
                    probe.close()
                    raise
                bind_host = "[::]"
            except OSError:
                probe = socket.socket(socket.AF_INET,
                                      socket.SOCK_STREAM)
                probe.bind(("0.0.0.0", 0))
                bind_host = "0.0.0.0"
            port = probe.getsockname()[1]
            probe.close()
            try:
                # cluster_register_timeout far beyond any client's
                # init_timeout: a member dying inside group formation
                # must surface as each surviving client's LOCAL
                # connect timeout (a catchable exception -> degraded
                # start), never as the service's barrier deadline,
                # which is pushed to registered clients as a FATAL
                # error (client.h:80 terminates them).
                try:
                    svc = jaxlib_ext.get_distributed_runtime_service(
                        f"{bind_host}:{port}", world,
                        cluster_register_timeout=24 * 3600)
                except TypeError:  # older jaxlib without the kwarg
                    svc = jaxlib_ext.get_distributed_runtime_service(
                        f"{bind_host}:{port}", world)
            except Exception as e:  # noqa: BLE001 — port race: retry
                last = e
                continue
            self._jaxsvcs.append(svc)
            log("tracker: hosting jax coordination service #%d on "
                "port %d", len(self._jaxsvcs), port)
            return port
        log("tracker: cannot host jax coordination service "
            "(5 attempts): %s", last)
        return 0

    # -- live telemetry exposition (GET /metrics, GET /status) ---------
    def _start_obs_server(self, port: int) -> None:
        """Serve the live telemetry plane on a tiny stdlib HTTP server
        (its own daemon threads — a slow scraper never touches the
        accept loop or the sweeps).  A bind failure degrades to "no
        exposition" with a log line, never a dead tracker."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        tracker = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib naming
                try:
                    if self.path.split("?")[0] in ("/metrics",):
                        body = tracker._render_metrics()
                        ctype = "text/plain; version=0.0.4"
                    elif self.path.split("?")[0] in ("/status",):
                        body = json.dumps(tracker._render_status(),
                                          sort_keys=True)
                        ctype = "application/json"
                    elif self.path.split("?")[0] in ("/trace",):
                        body = json.dumps(
                            tracker._render_trace(self.path),
                            sort_keys=True)
                        ctype = "application/json"
                    elif self.path.split("?")[0] in ("/", "/healthz"):
                        body, ctype = "ok\n", "text/plain"
                    else:
                        extra = tracker._render_http_extra(
                            self.path.split("?")[0])
                        if extra is None:
                            self.send_error(404)
                            return
                        body, ctype = extra
                except Exception as e:  # noqa: BLE001 — scrape survives
                    log("tracker: obs scrape failed: %s: %s",
                        type(e).__name__, e)
                    self.send_error(500, type(e).__name__)
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):  # noqa: N802 — stdlib naming
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(n) or b"{}")
                    doc = tracker._handle_http_post(
                        self.path.split("?")[0], body)
                except Exception as e:  # noqa: BLE001 — serve thread
                    log("tracker: obs POST %s failed: %s: %s",
                        self.path, type(e).__name__, e)
                    self.send_error(500, type(e).__name__)
                    return
                if doc is None:
                    self.send_error(404)
                    return
                data = json.dumps(doc, sort_keys=True).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *_a):  # silence per-request stderr
                pass

        host = self.host if self.host not in ("::",) else "0.0.0.0"
        try:
            srv = ThreadingHTTPServer((host, port), _Handler)
        except OSError as e:
            log("tracker: cannot bind the obs exposition port %d on "
                "%s: %s (scrape endpoint disabled)", port, host, e)
            return
        srv.daemon_threads = True
        self._obs_server = srv
        self.obs_port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, name="rabit-obs-http",
                         daemon=True).start()
        log("tracker: obs exposition on http://%s:%d (/metrics, /status)",
            host, self.obs_port)

    def _render_http_extra(self, path: str) -> tuple[str, str] | None:
        """Subclass hook for extra obs-server GET paths — ``(body,
        content_type)`` or None for a 404.  ShardServer mirrors the
        directory snapshot here (``GET /directory``)."""
        return None

    def _handle_http_post(self, path: str, body: dict) -> dict | None:
        """Subclass hook for obs-server POST paths — a JSON-able reply
        dict or None for a 404.  ShardServer serves the shard-to-shard
        migration offer (``POST /migrate``) and the forwarded goodbye
        (``POST /goodbye``) here."""
        return None

    def _render_trace(self, path: str) -> dict:
        """``GET /trace``: per-job assembled-timeline summaries;
        ``GET /trace?job=NAME[&op=E,V,S,KIND]`` exports one job's
        newest (or named) op as a Perfetto-loadable Chrome-trace JSON
        object — the doc ``tools/trace_report.py`` analyzes."""
        from urllib.parse import parse_qs, urlsplit
        q = parse_qs(urlsplit(path).query)
        want = (q.get("job") or [None])[0]
        if want is None:
            jobs = {}
            for job in self._job_list():
                if job.touched:
                    try:
                        jobs[job.name] = job._traces.report()
                    except Exception as e:  # noqa: BLE001 — scrape survives
                        jobs[job.name] = {"error": type(e).__name__, "detail": str(e)}
            return {"jobs": jobs}
        job = self._job_get(want)
        if job is None:
            return {"error": "no such job", "job": want}
        key = None
        raw = (q.get("op") or [None])[0]
        if raw:
            try:
                e, v, s, kind = raw.split(",", 3)
                key = (int(e), int(v), int(s), kind)
            except ValueError:
                return {"error": "bad op key (want E,V,S,KIND)", "op": raw}
        doc = job._traces.chrome(key)
        doc["job"] = want
        return doc

    def _render_metrics(self) -> str:
        """The Prometheus text exposition: service counters plus every
        job's live per-rank fold, heartbeat freshness, straggler scores
        and per-schedule span latency (labels job/rank/sched).  Each
        job renders inside its own guard so one tenant's racing
        mutation can only drop its OWN series from one scrape."""
        samples: list[tuple[str, dict, float]] = []
        types: dict[str, str] = {"rabit_jobs_active": "gauge",
                                 "rabit_job_world": "gauge",
                                 "rabit_job_epoch": "gauge",
                                 "rabit_job_committed_version": "gauge",
                                 "rabit_job_members": "gauge",
                                 "rabit_hb_last_seen_seconds": "gauge",
                                 "rabit_straggler_score": "gauge",
                                 "rabit_sched_op_count": "counter",
                                 "rabit_sched_op_seconds_sum": "counter",
                                 "rabit_sched_skew_seconds_max": "gauge",
                                 "rabit_sched_active": "gauge",
                                 "rabit_rank_demoted": "gauge",
                                 "rabit_controller_decisions_total":
                                     "counter",
                                 "rabit_serve_requests_total": "counter",
                                 "rabit_serve_qos_requests_total":
                                     "counter",
                                 "rabit_serve_slo_burn_rate": "gauge",
                                 "rabit_serve_slo_budget_remaining":
                                     "gauge",
                                 "rabit_trace_ops_assembled_total":
                                     "counter",
                                 "rabit_trace_records_total": "counter",
                                 "rabit_trace_link_seconds_mean": "gauge",
                                 "rabit_trace_link_hops_total": "counter"}
        svc = self._service_report()
        samples.append(("rabit_jobs_active", {},
                        len(svc["jobs_active"])))
        for name, v in sorted(svc["counters"].items()):
            pname = obs.prom_name(name)
            types[pname] = "counter"
            samples.append((pname, {}, v))
        now = time.monotonic()
        for job in self._job_list():
            if not job.touched:
                continue
            try:
                base = {"job": job.name}
                samples += [
                    ("rabit_job_world", base, job.n_workers),
                    ("rabit_job_epoch", base, job._epoch),
                    ("rabit_job_committed_version", base,
                     job._committed_version),
                    ("rabit_job_members", base, len(job._members)),
                ]
                with job._hb_lock:
                    peers = dict(job._hb_peers)
                for task, p in sorted(peers.items()):
                    rank = job._rank_of.get(task)
                    lbl = {**base, "rank": str(rank)
                           if rank is not None else task}
                    samples.append(("rabit_hb_last_seen_seconds", lbl,
                                    max(now - p.last, 0.0)))
                for rank, row in job._live.rows():
                    lbl = {**base, "rank": str(rank)}
                    for name, v in sorted(row["counters"].items()):
                        # Serving-plane SLO counters render as ONE
                        # labeled series (doc/serving.md "SLOs"):
                        # serve.requests.<status> →
                        # rabit_serve_requests_total{status=...}, the
                        # shape dashboards sum/rate over.
                        if name.startswith("serve.requests."):
                            status = name[len("serve.requests."):]
                            if status and "." not in status:
                                samples.append(
                                    ("rabit_serve_requests_total",
                                     {**lbl, "status": status}, v))
                                continue
                        # Per-class serving books render the same way:
                        # serve.qos.<class>.<status> → one labeled
                        # rabit_serve_qos_requests_total{qos,status}
                        # series dashboards can sum by either label.
                        if name.startswith("serve.qos."):
                            cls, _, status = \
                                name[len("serve.qos."):].partition(".")
                            if cls and status and "." not in status:
                                samples.append(
                                    ("rabit_serve_qos_requests_total",
                                     {**lbl, "qos": cls,
                                      "status": status}, v))
                                continue
                        pname = obs.prom_name(name)
                        types.setdefault(pname, "counter")
                        samples.append((pname, lbl, v))
                    for name, v in sorted(row["gauges"].items()):
                        pname = obs.prom_name(name)
                        types.setdefault(pname, "gauge")
                        samples.append((pname, lbl, v))
                # ONE report() per job per scrape: every sub-section
                # below reads the same snapshot (the merger lock sits
                # on the frame-ingest hot path).
                span_rep = job._spans.report()
                # Straggler scores max-merge the training-plane span
                # fold with the serving-plane batch-service fold
                # (serve.svc_ewma_ms over the fleet median): a rank
                # slow on EITHER plane scores high, and serve-only
                # jobs (no spans at all) still get a series the
                # loadgen router can route away from.
                serve_scores = {str(r): s for r, s in
                                obs.serve_straggler_scores(
                                    job._live.rows()).items()}
                for rank, row in span_rep["ranks"].items():
                    samples.append(("rabit_straggler_score",
                                    {**base, "rank": rank},
                                    max(row["score"],
                                        serve_scores.pop(str(rank),
                                                         0.0))))
                for rank, score in sorted(serve_scores.items()):
                    samples.append(("rabit_straggler_score",
                                    {**base, "rank": rank}, score))
                for sched, st in span_rep["sched"].items():
                    lbl = {**base, "sched": sched}
                    samples += [
                        ("rabit_sched_op_count", lbl, st["count"]),
                        ("rabit_sched_op_seconds_sum", lbl,
                         st["count"] * st["mean_sec"]),
                        ("rabit_sched_skew_seconds_max", lbl,
                         st["max_skew_sec"]),
                    ]
                # Adaptive controller: the currently-active directive
                # (one series per payload bucket), demotions and the
                # decision counters.
                for bucket, sname in sorted(job._active_sched.items()):
                    samples.append(("rabit_sched_active",
                                    {**base, "sched": sname,
                                     "bucket": str(bucket)}, 1))
                for rank in sorted(job._demoted):
                    samples.append(("rabit_rank_demoted",
                                    {**base, "rank": str(rank)}, 1))
                if job._controller is not None:
                    for kind, n in sorted(
                            job._controller.counters.items()):
                        samples.append(
                            ("rabit_controller_decisions_total",
                             {**base, "kind": kind}, n))
                # Serving SLO burn rows (doc/observability.md "Serving
                # SLO"): derived from the per-rank shed/timeout/error
                # counters the live fold already holds.  Per-job labels
                # keep the shard-level page merge exact (jobs are
                # disjoint across shards).
                slo = obs.serve_slo(job._live.rows(),
                                    self._serve_slo_target)
                if slo is not None:
                    samples += [
                        ("rabit_serve_slo_burn_rate", base,
                         slo["burn_rate"]),
                        ("rabit_serve_slo_budget_remaining", base,
                         slo["budget_remaining"]),
                    ]
                # Causal trace plane: assembly totals plus the folded
                # per-link cost table (mean hop seconds + hop counts per
                # directed link) — the same evidence /trace exports.
                if job._traces.records:
                    samples += [
                        ("rabit_trace_ops_assembled_total", base,
                         job._traces.assembled),
                        ("rabit_trace_records_total", base,
                         job._traces.records),
                    ]
                    for link, row in job._traces.link_costs().items():
                        lbl = {**base, "link": link}
                        samples += [
                            ("rabit_trace_link_seconds_mean", lbl,
                             row["mean_sec"]),
                            ("rabit_trace_link_hops_total", lbl,
                             row["n"]),
                        ]
            except Exception as e:  # noqa: BLE001 — one tenant's racing
                log("tracker:%s metrics render skipped this scrape: %s",
                    job._tag(), e)  # mutation must not 500 the scrape
        return obs.prometheus_text(samples, types)

    def _render_status(self) -> dict:
        """The ``GET /status`` JSON: the facts soak.py derives from the
        outside (members, epoch, committed version, liveness verdicts,
        admission counters), queryable live per job."""
        out = {"ts": time.time(), "service": self._service_report(),
               "elastic": self._elastic, "jobs": {}}
        now = time.monotonic()
        for job in self._job_list():
            if not job.touched:
                continue
            try:
                with job._hb_lock:
                    peers = dict(job._hb_peers)
                liveness = {}
                for task, p in sorted(peers.items()):
                    liveness[task] = {
                        "rank": job._rank_of.get(task),
                        "last_seen_sec": round(max(now - p.last, 0.0), 3),
                        "dead": p.dead,
                    }
                span_rep = job._spans.report()
                scores = {r: round(row["score"], 3)
                          for r, row in span_rep["ranks"].items()}
                for r, s in obs.serve_straggler_scores(
                        job._live.rows()).items():
                    r = str(r)
                    scores[r] = round(max(scores.get(r, 0.0), s), 3)
                flagged = {str(r) for r in job._straggling}
                out["jobs"][job.name] = {
                    "world": job.n_workers,
                    "epoch": job._epoch,
                    "committed_version": job._committed_version,
                    "done": job.done,
                    "members": sorted(job._members),
                    "shutdown": sorted(job._shutdown_tasks),
                    "lost": sorted(job._lost_tasks),
                    "liveness": liveness,
                    "live": job._live.report(),
                    "stragglers": {r: s for r, s in scores.items()
                                   if r in flagged},
                    "straggler_scores": scores,
                    "merged_ops": span_rep["merged_ops"],
                    "sched_latency": span_rep["sched"],
                }
                # Causal trace plane: bound-by verdict, per-link cost
                # table and the newest assembled timeline — what
                # rabit_top's bound-by column and --trace read, and
                # what merge_status_docs folds shard-level (the section
                # rides the per-job row; jobs are disjoint).
                if job._traces.records:
                    out["jobs"][job.name]["trace"] = job._traces.report()
                slo = obs.serve_slo(job._live.rows(),
                                    self._serve_slo_target)
                if slo is not None:
                    out["jobs"][job.name]["serve_slo"] = slo
                # Adaptive controller: active directive, demotions and
                # the recent decision records with their evidence — the
                # facts soak.py's --adapt gate (and rabit_top's "active
                # sched / last decision" display) derive from outside.
                ctl = job._controller
                if ctl is not None or job._active_sched or job._demoted:
                    out["jobs"][job.name]["controller"] = {
                        "active_sched": {
                            str(b): s for b, s
                            in sorted(job._active_sched.items())},
                        "demoted": sorted(job._demoted),
                        "decisions": ([d.as_dict()
                                       for d in list(ctl.decisions)[-8:]]
                                      if ctl is not None else []),
                        "counters": (dict(ctl.counters)
                                     if ctl is not None else {}),
                    }
            except Exception as e:  # noqa: BLE001 — see _render_metrics
                out["jobs"][job.name] = {"error": type(e).__name__}
        return out

    def _dump_trace_journal(self, job: "JobState") -> None:
        """One job's control-plane side of the postmortem record
        (``--trace-dir``): the liveness/recovery timeline plus the
        assembled trace summary, written atomically next to the
        workers' flight records for ``tools/postmortem.py`` to merge.
        Best effort — teardown never dies in its own forensics."""
        if not self._trace_dir:
            return
        doc = {"job": job.name, "ts": round(time.time(), 6),
               "world": job.n_workers, "epoch": job._epoch,
               "committed_version": job._committed_version,
               "members": sorted(job._members),
               "lost": sorted(job._lost_tasks),
               "events": list(job._events)[-512:]}
        try:
            doc["trace"] = job._traces.report()
        except Exception as e:  # noqa: BLE001 — forensics stay best effort
            doc["trace"] = {"error": type(e).__name__, "detail": str(e)}
        name = job.name if job.name != "default" else "default"
        path = os.path.join(self._trace_dir, f"tracker.{name}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self._trace_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError as e:
            log("tracker: trace journal dump failed: %s", e)

    def _close_all(self) -> None:
        # Jobs interrupted mid-flight (stop() / permanent failure)
        # still get their telemetry written; finished jobs already
        # wrote theirs at completion.
        if getattr(self, "_trace_dir", None):
            for job in self._job_list():
                if job.touched:
                    self._dump_trace_journal(job)
        srv = getattr(self, "_obs_server", None)
        if srv is not None:
            try:
                srv.shutdown()
                srv.server_close()
            except OSError:
                pass
            self._obs_server = None
        for job in self._job_list():
            if job.touched and not job.done:
                job._write_obs_report()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._jaxsvc_lock:
            svcs, self._jaxsvcs = self._jaxsvcs, []
            for svc in svcs:
                try:
                    svc.shutdown()
                except Exception as e:  # noqa: BLE001 — best-effort stop
                    log("tracker: jax service shutdown failed: %s", e)
        for job in self._job_list():
            job.close()

    def _watchdog(self) -> None:
        """Fires on_stall when a rendezvous round sits partially filled
        longer than watchdog_sec.  Restarting a merely-slow worker is
        wasteful but safe (it reloads from its checkpoint), so the
        launcher may use an aggressive bound in test/dev jobs."""
        while not self._stopped:
            time.sleep(min(0.2, self._watchdog_sec / 5))
            for job in self._live_jobs():
                with job._pending_lock:
                    stalled = (
                        job._round_started is not None
                        and 0 < len(job._pending) < job._round_size()
                        and time.monotonic() - job._round_started
                        > self._watchdog_sec)
                    if not stalled:
                        continue
                    present = {r.task_id for r in job._pending}
                    finished = set(job._shutdown_tasks)
                    # rearm: fire again only after another full period
                    job._round_started = time.monotonic()
                log("tracker:%s rendezvous stalled (%d/%d registered); "
                    "notifying launcher", job._tag(), len(present),
                    job._round_size())
                try:
                    self._on_stall(present, finished)
                except Exception as e:  # noqa: BLE001 — must survive
                    log("tracker: on_stall callback failed: %s", e)

    # How often the adaptive controller re-scores each job's schedule
    # choice from the live span fold (tracker --adapt).
    ADAPT_SWEEP_SEC = 0.5

    def _adapt_loop(self) -> None:
        """The adaptive controller's sweep: one `_adapt_tick` per live
        job per period, each inside its own guard — one tenant's racing
        mutation must never stall a co-tenant's adaptation."""
        while not self._stopped:
            time.sleep(self.ADAPT_SWEEP_SEC)
            for job in self._active_jobs():
                try:
                    job._adapt_tick()
                except Exception as e:  # noqa: BLE001 — sweep survives
                    log("tracker:%s adapt tick failed: %s: %s",
                        job._tag(), type(e).__name__, e)

    def _tune_merge(self, kind: str, world: int, nbytes: int,
                    name: str, transport: str = "tcp",
                    codec: str = "none") -> None:
        """Fold one controller verdict into the shared TuningCache and
        atomically re-persist it (tracker --tune-dir), so the NEXT
        ``rabit_sched=auto`` job starts on the learned schedule.
        ``transport`` and ``codec`` (from the job's streamed frames)
        key the rows — a winner measured over shm rings never answers a
        tcp world, nor an int8-wire winner a full-width job.
        Best-effort: a full disk degrades warm starts, never the
        running job."""
        if self._tuning_cache is None:
            return
        with self._tune_lock:
            self._tuning_cache.merge_online(kind, world, nbytes, name,
                                            transport=transport,
                                            codec=codec)
            if self._tune_dir:
                try:
                    self._tuning_cache.save(self._tune_dir)
                except OSError as e:
                    log("tracker: tuning cache persist failed: %s", e)
        self._count("controller.tune_merges")

    # How often parked rendezvous sockets are polled for death (and
    # job completion / orphan GC is re-checked).
    REGISTRANT_SWEEP_SEC = 0.5

    def _sweep_registrants(self) -> None:
        """Per-job dead-registrant sweep + the job lifecycle sweep
        (completion backstop and the idle-orphan GC)."""
        while not self._stopped:
            time.sleep(self.REGISTRANT_SWEEP_SEC)
            now = time.monotonic()
            for job in self._live_jobs():
                # One tenant's corrupt state must never kill the sweep
                # for its co-tenants (fault isolation): failures are
                # logged per job and the pass moves on.
                try:
                    job.sweep_registrants_once()
                    if not job.touched:
                        continue  # lifecycle starts at first admission
                    if job.job_done():
                        self._finish_job(job, "finished")
                        continue
                    why = job.orphaned(now)
                    if why is not None:
                        log("tracker:%s orphan GC: %s", job._tag(), why)
                        self._finish_job(job, "orphan_gc")
                except Exception as e:  # noqa: BLE001 — sweep survives
                    log("tracker:%s registrant/lifecycle sweep failed: "
                        "%s", job._tag(), e)
            # Exit-condition backstop: job completion and linger expiry
            # can both happen while run() is blocked in accept().
            if self._service_done():
                self._wake_accept()

    # -- heartbeat failure detector ------------------------------------
    # How often the heartbeat sweep wakes to drain beats and check
    # deadlines; detection latency adds at most one sweep period on top
    # of the miss budget.
    HB_SWEEP_SEC = 0.1

    def _hb_monitor(self) -> None:
        """Drain beats and run the deadline-based suspicion sweep,
        across every job's heartbeat channels."""
        while not self._stopped:
            pairs: list[tuple[JobState, _HbPeer]] = []
            for job in self._live_jobs():
                with job._hb_lock:
                    pairs.extend((job, p)
                                 for p in job._hb_peers.values())
            if not pairs:
                time.sleep(self.HB_SWEEP_SEC)
                continue
            sel = selectors.DefaultSelector()
            try:
                for job, p in pairs:
                    try:
                        sel.register(p.sock, selectors.EVENT_READ,
                                     (job, p))
                    except (OSError, ValueError):
                        continue  # closed under us; deadline still runs
                try:
                    ready = [key.data
                             for key, _ in sel.select(self.HB_SWEEP_SEC)]
                except OSError:
                    # a registered fd closed mid-select (tracker
                    # teardown race): the detector must outlive it
                    ready = []
            finally:
                sel.close()
            if self._stopped:
                return  # teardown: sockets are closing under us; any
                # drain from here would just log spurious EOFs
            now = time.monotonic()
            for job, p in ready:
                try:
                    job._hb_drain(p, now)
                except Exception as e:  # noqa: BLE001 — see sweep note
                    log("tracker:%s heartbeat drain failed for task %r: "
                        "%s", job._tag(), p.task_id, e)
            for job, p in pairs:
                with job._hb_lock:
                    if job._hb_peers.get(p.task_id) is not p:
                        continue  # replaced (relaunch) or forgotten
                if now - p.last > p.period_s * self._hb_miss:
                    try:
                        job._hb_mark_dead(
                            p, "dead",
                            f"no beat for {now - p.last:.2f}s (budget "
                            f"{self._hb_miss:g} x {p.period_s:g}s)")
                    except Exception as e:  # noqa: BLE001
                        log("tracker:%s heartbeat verdict failed for "
                            "task %r: %s", job._tag(), p.task_id, e)

    # -- command dispatch ----------------------------------------------
    def _handle(self, sock: socket.socket) -> None:
        try:
            job_name, cmd, task_id, world_hint = P.recv_hello(sock)
        except P.HandshakeError as e:
            # Stray client on the tracker port (port scanner, HTTP
            # probe, corrupt worker): log + drop; a client that spoke
            # the magic gets the typed reject so a confused worker
            # fails loudly instead of waiting on a closed socket.
            self._count("job.handshake.dropped")
            log("tracker: dropped stray client on the tracker port (%s)",
                e)
            if e.parsed_magic:
                try:
                    P.RejectReply(P.REJECT_BAD_HANDSHAKE, str(e)).send(sock)
                except OSError:
                    pass
            try:
                sock.close()
            except OSError:
                pass
            return
        try:
            self._dispatch(sock, job_name, cmd, task_id, world_hint)
        except P.HandshakeError as e:
            # Post-magic garbage (oversized host string, corrupt print
            # payload length): same typed-reject treatment as a hello
            # that went wrong after the magic — the client is told
            # loudly instead of timing out its whole retry budget on a
            # silent close, and the stray is counted.
            self._count("job.handshake.dropped")
            log("tracker: dropped malformed %s from task %r (%s)",
                cmd, task_id, e)
            try:
                P.RejectReply(P.REJECT_BAD_HANDSHAKE, str(e)).send(sock)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def _dispatch(self, sock: socket.socket, job_name: str, cmd: str,
                  task_id: str, world_hint: int) -> None:
        if cmd == P.CMD_PRINT:
            # Print payloads (incl. multi-KB obs summaries) get a
            # generous but finite cap — a stray length prefix must not
            # become an unbounded buffering recv.
            msg = P.recv_str(sock, max_len=P.MAX_PRINT_LEN)
            job = self._job_get(job_name)
            if msg.startswith(obs.OBS_SUMMARY_PREFIX):
                if job is not None:
                    job.last_activity = time.monotonic()
                    job._obs_ingest(msg[len(obs.OBS_SUMMARY_PREFIX):])
            else:
                sys.stdout.write(msg if msg.endswith("\n")
                                 else msg + "\n")
                sys.stdout.flush()
            sock.close()
            return
        if cmd == P.CMD_SHUTDOWN:
            job = self._job_get(job_name)
            if job is not None:
                job.last_activity = time.monotonic()
                if task_id in job._rank_of:
                    job._shutdown_tasks.add(task_id)
                if job.job_done():
                    # _finish_job journals the terminal (done=True)
                    # state — no point fsyncing an immediately
                    # superseded snapshot first.
                    self._finish_job(job, "finished")
                elif task_id in job._rank_of:
                    job._journal()
            sock.close()
            return
        if cmd == P.CMD_EPOCH:
            # Membership poll (one-shot): record the worker's committed
            # version (journaled job progress), reply the current and
            # pending epoch so commit boundaries learn about rescales.
            version = P.recv_u32(sock)
            job = self._job_get(job_name)
            if job is None:
                try:  # unknown/finished job: "no change"
                    P.send_u32(sock, 0)
                    P.send_u32(sock, 0)
                    P.send_u32(sock, 0)
                except OSError:
                    pass
                sock.close()
                return
            job.last_activity = time.monotonic()
            bump = version > job._committed_version
            if bump:
                job._committed_version = version
            with job._scale_lock:
                pending = job._target_world is not None
                target_epoch = job._epoch + (1 if pending else 0)
                target_world = (job._target_world if pending
                                else job.n_workers)
            try:
                P.send_u32(sock, job._epoch)
                P.send_u32(sock, target_epoch)
                P.send_u32(sock, target_world)
            except OSError:
                pass  # poller gone; it treats that as "no change"
            sock.close()
            if bump:
                job._journal()
            return
        if cmd == P.CMD_JAXSVC:
            job = self._job_get(job_name)
            P.send_u32(sock, job.keyed_jax_service(task_id)
                       if job is not None else 0)
            sock.close()
            return
        if cmd == P.CMD_FORMBAR:
            job = self._job_get(job_name)
            if job is None:
                JobState._formbar_reply(sock, False)
                return
            job.last_activity = time.monotonic()
            job._formbar_post(sock, task_id)
            return
        if cmd == P.CMD_HEARTBEAT:
            period_ms = P.recv_u32(sock)
            job = self._job_get(job_name)
            if job is None:
                sock.close()
                return
            job.last_activity = time.monotonic()
            job._hb_register(sock, task_id, period_ms)
            return  # the connection stays open for the beat stream
        if cmd in (P.CMD_START, P.CMD_RECOVER, P.CMD_RESCALE):
            host = P.recv_str(sock, max_len=P.MAX_HELLO_STR)
            port = P.recv_u32(sock)
            try:
                job = self._admit(job_name, world_hint)
            except _AdmissionReject as rej:
                self._last_reject = time.monotonic()
                self._count("job.admission.rejected")
                self._count(f"job.admission.rejected.{rej.kind}")
                log("tracker: admission rejected %s of task %r: %s",
                    cmd, task_id, rej.reason)
                try:
                    P.RejectReply(rej.code, rej.reason).send(sock)
                except OSError:
                    pass
                sock.close()
                return
            job.register(sock, cmd, task_id, host, port)
            return
        log("tracker: unknown command %r from task %r", cmd, task_id)
        sock.close()


def _job_alias(attr: str):
    """Legacy single-job attribute surface: ``tracker.<attr>`` reads and
    writes the DEFAULT job's state (tests, tools and the embedded
    launchers predate multi-tenancy and address the tracker as if it
    served exactly one job — for them it still does)."""
    return property(
        lambda self: getattr(self._default_job(), attr),
        lambda self, value: setattr(self._default_job(), attr, value),
        doc=f"default job's ``{attr}`` (legacy single-job surface)")


for _attr in ("n_workers", "_rank_of", "_shutdown_tasks", "_members",
              "_started_tasks", "_pending", "_round_started",
              "_pending_lock", "_formbar_state", "_formbar_socks",
              "_formbar_posted", "_formbar_timer", "_formbar_lock",
              "_hb_peers", "_hb_seen", "_hb_lock", "_events",
              "_target_world", "_dead_tasks", "_joiners", "_lost_tasks",
              "_scale_lock", "_round_lock", "_committed_version",
              "_state_store", "_state_seq", "_journal_lock",
              "_obs_reports", "_obs_lock", "_jaxsvc_keyed",
              "_live", "_spans", "_straggling", "_controller",
              "_active_sched", "_demoted", "_sched_switch_pending",
              "_last_groups"):
    setattr(Tracker, _attr, _job_alias(_attr))
del _attr


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="rabit_tpu rendezvous tracker")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--obs-dir", default=None,
                    help="write the aggregated per-job telemetry report "
                         "(obs_report.json; named jobs nest under "
                         "<obs-dir>/<job>/) here; defaults to "
                         "RABIT_OBS_DIR when set")
    ap.add_argument("--min-workers", type=int, default=None,
                    help="elastic floor (per job): heartbeat-detected "
                         "deaths scale the world DOWN (never below "
                         "this) instead of waiting for a same-rank "
                         "relaunch")
    ap.add_argument("--max-workers", type=int, default=None,
                    help="elastic ceiling (per job): late cmd=start "
                         "registrants are admitted as joiners at the "
                         "next checkpoint-commit rescale, up to this "
                         "world")
    ap.add_argument("--state-dir", default=None,
                    help="journal the tracker state (rank map, epoch, "
                         "members, barriers; one journal per job) "
                         "through the atomic checkpoint-store tier; a "
                         "restarted tracker on the same port replays "
                         "every in-flight job and the workers' connect "
                         "retry bridges the outage")
    ap.add_argument("--max-jobs", type=int, default=None,
                    help="admission control: maximum concurrently "
                         "active jobs; an over-capacity submission "
                         "gets a typed reject reply (workers surface "
                         "it as AdmissionError after their retry "
                         "budget) and is re-admitted as soon as a "
                         "finishing job drains")
    ap.add_argument("--max-total-workers", type=int, default=None,
                    help="admission control: cap on the sum of all "
                         "active jobs' world sizes")
    ap.add_argument("--job-gc-sec", type=float, default=None,
                    help="orphan sweep: GC a job whose last member "
                         "vanished (no live heartbeat channels, every "
                         "member holding a death verdict) after this "
                         "long idle (default 30, env RABIT_JOB_GC_SEC)")
    ap.add_argument("--obs-port", type=int, default=None,
                    help="serve the live telemetry plane on this port "
                         "(0 = ephemeral): GET /metrics is the "
                         "Prometheus text exposition (labels "
                         "job/rank/sched), GET /status the per-job "
                         "JSON state; tools/rabit_top.py polls it "
                         "(doc/observability.md 'Live telemetry')")
    ap.add_argument("--straggler-factor", type=float, default=None,
                    help="straggler verdict threshold: a rank whose "
                         "rolling mean lateness across merged "
                         "collective spans exceeds this many op-times "
                         "gets a straggler event (default 3, env "
                         "RABIT_STRAGGLER_FACTOR)")
    ap.add_argument("--adapt", action="store_true",
                    help="arm the adaptive controller: re-score each "
                         "job's schedule choice online from the merged "
                         "collective spans, push schedule-switch "
                         "epochs at commit boundaries (workers need "
                         "rabit_adapt=1) and demote persistent "
                         "stragglers out of hierarchical leader roles "
                         "(doc/performance.md 'Online adaptation')")
    ap.add_argument("--tune-dir", default=None,
                    help="load-or-create the schedule TuningCache here "
                         "and atomically re-persist what the adaptive "
                         "controller learns, so the next "
                         "rabit_sched=auto job starts warm (same "
                         "format as bench.py --tune-dir)")
    ap.add_argument("--trace-dir", default=None,
                    help="postmortem directory: dump each job's "
                         "control-plane journal (liveness/recovery "
                         "timeline + assembled trace summary) here at "
                         "teardown, next to the workers' flight "
                         "records (RABIT_TRACE_DIR), for tools/"
                         "postmortem.py (doc/observability.md 'Causal "
                         "tracing & postmortem')")
    ap.add_argument("--directory", default=None,
                    help="base URL of the job directory service "
                         "(python -m rabit_tpu.tracker.directory): run "
                         "as ONE SHARD of the partitioned control "
                         "plane instead of a lone tracker — host only "
                         "the jobs the consistent-hash ring assigns "
                         "here, redirect the rest with typed "
                         "REJECT_SHARD_MOVED replies, and adopt a dead "
                         "peer's journals from the shared --state-dir "
                         "(doc/fault_tolerance.md 'Sharded tracker')")
    ap.add_argument("--shard-index", type=int, default=None,
                    help="this shard's stable index on the ring "
                         "(required with --directory; survives "
                         "restarts so a supervised shard relaunch "
                         "reclaims its own arc)")
    ap.add_argument("--migrate-after-sec", type=float, default=None,
                    help="live-migration threshold (shards only): a "
                         "RUNNING job whose ring owner has been "
                         "another shard for this long is handed to it "
                         "at a commit boundary (journal shipped, "
                         "workers redirected).  Unset = jobs stay "
                         "sticky until they finish (the default)")
    ap.add_argument("--migrate-max", type=int, default=2,
                    help="max live migrations per poll tick (bounds "
                         "the drain-and-move pass after a cold "
                         "restart or scale-up)")
    args = ap.parse_args(argv)
    common = dict(obs_dir=args.obs_dir, min_workers=args.min_workers,
                  max_workers=args.max_workers, state_dir=args.state_dir,
                  max_jobs=args.max_jobs,
                  max_total_workers=args.max_total_workers,
                  job_gc_sec=args.job_gc_sec, obs_port=args.obs_port,
                  straggler_factor=args.straggler_factor,
                  adapt=args.adapt, tune_dir=args.tune_dir,
                  trace_dir=args.trace_dir)
    if args.directory is not None:
        if args.shard_index is None:
            ap.error("--directory requires --shard-index")
        from rabit_tpu.tracker.shard import ShardServer
        tr: Tracker = ShardServer(args.num_workers, args.host,
                                  args.port,
                                  shard_index=args.shard_index,
                                  directory=args.directory,
                                  migrate_after_sec=args.migrate_after_sec,
                                  migrate_max=args.migrate_max,
                                  **common)
        sys.stdout.write(
            f"shard {args.shard_index} listening on "
            f"{tr.host}:{tr.port}"
            + (f" (obs on :{tr.obs_port})" if tr.obs_port else "")
            + f" [directory {args.directory}]\n")
    else:
        if args.shard_index is not None:
            ap.error("--shard-index requires --directory")
        tr = Tracker(args.num_workers, args.host, args.port, **common)
        sys.stdout.write(
            f"tracker listening on {tr.host}:{tr.port}"
            + (f" (obs on :{tr.obs_port})" if tr.obs_port else "")
            + "\n")
    sys.stdout.flush()
    tr.run()


if __name__ == "__main__":
    main()
