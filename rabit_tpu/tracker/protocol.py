"""Wire protocol between workers and the tracker.

A fresh design (not the reference's ad-hoc handshake, though it serves the
same role — reference: src/allreduce_base.cc:138-158 ConnectTracker and
tracker/rabit_tracker.py:47-122): little-endian length-prefixed primitives
chosen so the C++ native engine can speak it with a few dozen lines and no
JSON dependency.

All integers are u32 little-endian.  Strings are u32 length + utf-8 bytes.

Worker → tracker, on every fresh tracker connection:

    u32 magic       MAGIC (protocol/version gate), or MAGIC_JOB for the
                    multi-tenant hello — then `str job` follows
                    immediately (the tenant this connection belongs to,
                    [A-Za-z0-9._-], 64 chars max).  A worker whose job
                    id is the DEFAULT_JOB sends the plain MAGIC hello,
                    so the default-tenant byte stream is IDENTICAL to
                    the pre-multi-tenant wire in both directions: old
                    workers land in the "default" job on a new tracker,
                    and a new worker without a job id still speaks to
                    an old tracker.
    str cmd         "start" | "recover" | "rescale" | "print" | "shutdown"
    str task_id     stable worker identity (rank reassignment on restart)
    u32 world       world size the worker was launched with (0 = unknown)

then, for cmd in {start, recover, rescale}:

    str host        worker's listening address
    u32 port        worker's listening port

The tracker length-caps and charset-checks every handshake read
(:func:`recv_hello`): a stray client on the tracker port (port scanner,
HTTP probe) is logged and dropped at the magic check, and a client that
passed the magic but sent garbage lengths / non-utf-8 gets a typed
reject reply (:class:`RejectReply`, code ``REJECT_BAD_HANDSHAKE``)
instead of wedging or crashing the accept thread.

tracker → worker reply (start/recover/rescale only) — EITHER a reject
frame (the first u32 is the REJECT sentinel, which can never be a real
rank):

    u32 REJECT      0xFFFFFFFE
    u32 code        REJECT_* (admission / handshake)
    str reason      human-readable detail

— sent when admission control (tracker --max-jobs /
--max-total-workers) refuses the job; workers retry it with backoff
and surface a typed ``AdmissionError`` once the budget is spent
(engine/pysocket.py) — or the topology:

    u32 rank
    u32 world
    u32 parent      tree parent rank, NONE if root
    u32 nneighbor   tree neighbor count, then that many u32 ranks
    u32 ring_prev   ring predecessor rank
    u32 ring_next   ring successor rank
    u32 nconnect    peers to actively connect: (u32 rank, str host, u32 port)*
    u32 naccept     number of inbound connections to expect
    u32 relaunched  1 iff this is a cmd=start re-registration of a task_id
                    that already completed a rendezvous round — i.e. a
                    mid-job relaunch.  Lets engines detect relaunch even
                    when the platform restarts workers with a clean
                    environment (no RABIT_NUM_TRIAL/RABIT_RELAUNCH).
    u32 epoch       the membership epoch this topology belongs to; bumped
                    every time the tracker completes a RESCALE round
                    (world grew or shrank, ranks reassigned).  Trailing
                    field on purpose: a reader of the pre-elastic layout
                    simply leaves it unread on the one-shot socket.
    u32 ngroups     host-group handout for the topology-aware schedules:
                    one group id per rank (ranks on the same host share
                    an id — or the RABIT_TRACKER_GROUPS override), then
                    that many u32 ids.  The hierarchical two-level
                    schedule keys off it (rabit_tpu/sched/hier.py).
                    Trailing like epoch: older readers leave it unread.
    str sched       live schedule directive from the tracker's adaptive
                    controller ("" = none): per-payload-bucket override
                    entries "bytes:name,..." the engine consults before
                    its static/auto pick (sched/tuner.py
                    decode_directive; doc/performance.md "Online
                    adaptation").  Pushed to the whole world together
                    at a schedule-switch epoch.
    u32 ndemoted    straggler-demoted ranks (then that many u32 ranks):
                    excluded from hierarchical leader election on every
                    rank identically (sched/topo.py group_leader).
                    Both fields are trailing like epoch/groups — and
                    the READER also tolerates their absence (a
                    pre-adaptive tracker closes the one-shot socket
                    after groups; the worker defaults to no directive).

for cmd == "print": str message follows, no reply.
for cmd == "shutdown": nothing follows, no reply.
for cmd == "heartbeat": u32 period_ms follows, then the connection stays
    OPEN (the one persistent tracker connection) carrying one u32 beat
    per period; HEARTBEAT_BYE closes it cleanly at worker shutdown.
    EOF without the bye, or a missed-beat budget, marks the worker dead
    on the control plane (tracker/tracker.py heartbeat sweep).
    Telemetry-streaming workers multiplex **obs frames** onto the same
    byte stream: u32 HEARTBEAT_OBS, u32 length, then ``length`` bytes
    of JSON padded with spaces to a u32 boundary (delta metric
    snapshot + buffered collective spans — doc/observability.md "Live
    telemetry").  Frames count as liveness like beats.  Once a worker
    has sent any obs frame the tracker ECHOES each subsequent beat
    number back on the connection (best-effort, dropped when the
    socket buffer is full); the worker measures the round trip as its
    ``hb.rtt.seconds`` histogram.  A pre-obs tracker reads a frame as
    a run of meaningless beat values — the padding keeps the stream
    u32-ALIGNED, and no aligned payload word can collide with
    HEARTBEAT_BYE (ASCII JSON + 0x20 padding), so the worker's real
    BYE is still recognized; a pre-obs worker never sends the sentinel
    nor reads echoes.  The channel stays compatible in both
    directions.

Worker ↔ worker, on each data link after connect:

    u32 magic, u32 own_rank     (both directions; ranks identify links)
"""
from __future__ import annotations

import re
import socket
import struct
from dataclasses import dataclass, field

MAGIC = 0x7AB17901
# Multi-tenant hello: `str job` follows the magic, then the classic
# layout (cmd, task_id, world, ...).  Only sent when the job id is not
# DEFAULT_JOB, so default-tenant traffic is byte-identical to the
# pre-multi-tenant wire (back-compat both directions).
MAGIC_JOB = 0x7AB17908
NONE = 0xFFFFFFFF

# The implicit tenant of every classic (MAGIC) hello.
DEFAULT_JOB = "default"
# Job ids become directory names (obs/<job>/, state_dir/<job>/) and log
# tags: one path-safe token, no leading dot, bounded length.
_JOB_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]{0,63}\Z")
# Handshake string caps (recv_hello): task ids/commands/hosts are tens
# of bytes — a length prefix beyond this is a stray or hostile client,
# not a worker, and must not turn into an unbounded buffering recv.
MAX_HELLO_STR = 1024
# Print-channel payload cap: obs summaries are multi-KB JSON blobs, so
# the bound is generous — but still finite, so a corrupt length prefix
# cannot make the tracker buffer gigabytes.
MAX_PRINT_LEN = 8 << 20

# Reject reply sentinel: the first u32 of a registration reply is the
# assigned rank, which can never be this value (NONE is already taken
# by "no parent").  A reject frame follows: u32 code, str reason.
REJECT = 0xFFFFFFFE
REJECT_BAD_HANDSHAKE = 1   # parsed the magic, then garbage
REJECT_MAX_JOBS = 2        # admission: job count at --max-jobs
REJECT_MAX_WORKERS = 3     # admission: worker sum at --max-total-workers
# Sharded control plane (doc/fault_tolerance.md "Sharded tracker").
# Both codes only ever fire on a multi-shard deployment, so the
# single-shard wire stays byte-identical in both directions.
REJECT_SHARD_MOVED = 4     # job hashes to another shard; reason carries
#                            "gen=<G>;shard=<I>;endpoint=<host>:<port>"
#                            so a stale-directory client re-targets
#                            without a second directory round trip
REJECT_REPLAYING = 5       # shard mid-journal-replay (handoff adopt):
#                            typed backoff-retry, linger-covered — a
#                            submission racing an adoption never gets a
#                            silent close or a duplicate JobState

CMD_START = "start"
CMD_RECOVER = "recover"
CMD_PRINT = "print"
CMD_SHUTDOWN = "shutdown"
# "jaxsvc": rank 0 of the XLA engine asks the tracker to host a fresh
# JAX coordination service for the job's world size.  Reply: u32 port
# (0 = tracker cannot host, e.g. no jaxlib).  Hosting the service in
# the long-lived tracker decouples the device-plane coordinator from
# worker lifetimes: ANY worker's death — including rank 0's — is then a
# recoverable peer failure instead of a fatal loss of the coordination
# service.  Previous epochs' services are retained until the tracker
# closes (a degraded member may still be attached to one).
CMD_JAXSVC = "jaxsvc"
# "formbar": the formation barrier.  Each XLA-engine worker posts this
# as its LAST act before the blocking jaxlib group registration; the
# tracker replies u32 1 (proceed) only once every worker of the job has
# posted, and 0 (abort — start degraded) when any task re-registers as
# a mid-job relaunch or the barrier times out.  Needed because a client
# stuck in a doomed registration barrier cannot escape: when a
# co-registrant dies the coordination service's error push fatally
# terminates the blocked clients (jaxlib client.h:80), and the client's
# own init_timeout is routed through the same fatal path rather than
# raising.  So liveness is decided on the control plane BEFORE anyone
# blocks in the device-plane registration.
CMD_FORMBAR = "formbar"
# "heartbeat": the persistent liveness channel.  A worker opens ONE of
# these right after its first rendezvous, sends its period (u32 ms),
# then one u32 beat per period for the life of the process.  The
# tracker's deadline sweep marks a worker dead once
# rabit_heartbeat_miss periods pass without a beat — liveness is
# decided PROACTIVELY on the control plane, so a hung rank is evicted
# (and its supervisor notified) without any collective op having to
# touch it first.  A clean shutdown sends HEARTBEAT_BYE before close;
# EOF without the bye means the process died.
CMD_HEARTBEAT = "heartbeat"
HEARTBEAT_BYE = 0xFFFFFFFF
# Obs-frame sentinel on the heartbeat byte stream (see the module
# docstring): u32 HEARTBEAT_OBS, u32 length, JSON payload.  Never a
# plausible beat number (beats count up from 1) and distinct from the
# BYE sentinel.
HEARTBEAT_OBS = 0xFFFFFFFD
# "rescale": a current member re-registering for an elastic membership
# epoch (doc/fault_tolerance.md "Elastic membership & tracker HA").
# Same payload/reply as start/recover; the round it joins completes at
# the tracker's pending TARGET world (grown by admitted joiners, shrunk
# by heartbeat-detected deaths), ranks are reassigned deterministically
# (surviving members by old rank, then joiners by task_id) and the
# reply's epoch field is bumped.  Members enter this round together at
# a checkpoint-commit boundary (the K_RESCALE consensus bit — see
# engine/robust.py), so no in-flight collective ever spans two worlds.
CMD_RESCALE = "rescale"
# "epoch": one-shot membership poll.  u32 committed_version follows
# (the worker's current checkpoint version — the tracker journals the
# max as the job's committed progress); reply u32 epoch, u32
# target_epoch, u32 target_world.  target_epoch > epoch means a rescale
# is pending and the next commit boundary should re-rendezvous with
# cmd=rescale.  Best-effort on the worker side: an unreachable tracker
# (e.g. restarting) reads as "no change" — polling never stalls
# training.
CMD_EPOCH = "epoch"


class HandshakeError(ValueError):
    """A tracker-port client sent something that is not a worker hello.

    ``parsed_magic`` distinguishes a stray client (bad magic — an HTTP
    probe, a port scanner: log and drop, no reply owed) from a client
    that spoke the magic and then went wrong (oversized length prefix,
    non-utf-8, bad job id: it understands the protocol enough to be
    sent a typed ``REJECT_BAD_HANDSHAKE`` reply)."""

    def __init__(self, msg: str, parsed_magic: bool = False) -> None:
        super().__init__(msg)
        self.parsed_magic = parsed_magic


def valid_job_id(job: str) -> bool:
    """Path-safe single token (job ids name obs/journal directories)."""
    return bool(_JOB_ID_RE.match(job))


def require_valid_job_id(job) -> None:
    """Launcher-side early validation: fail before any worker spawns
    (each worker's own engine check would otherwise burn its restart
    budget on a config typo)."""
    if not valid_job_id(str(job)):
        raise ValueError(
            f"--job {job!r} is not a valid job id "
            "([A-Za-z0-9][A-Za-z0-9._-]{0,63})")


def send_all(sock: socket.socket, data: bytes) -> None:
    sock.sendall(data)


def recv_all(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionResetError("peer closed during recv")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_u32(sock: socket.socket, value: int) -> None:
    send_all(sock, struct.pack("<I", value))


def recv_u32_or_eof(sock: socket.socket) -> int | None:
    """Receive one u32 — or None on a CLEAN EOF at the field boundary
    (zero bytes read).  Optional-trailing-field reads use this to tell
    "the peer's protocol version simply ends here" (old tracker:
    default the field) apart from a genuine mid-field failure (raise —
    the caller must retry, not silently diverge from peers that read
    the full reply)."""
    buf = b""
    while len(buf) < 4:
        chunk = sock.recv(4 - len(buf))
        if not chunk:
            if not buf:
                return None
            raise ConnectionResetError("peer closed mid-field")
        buf += chunk
    return struct.unpack("<I", buf)[0]


def recv_u32(sock: socket.socket) -> int:
    return struct.unpack("<I", recv_all(sock, 4))[0]


def send_str(sock: socket.socket, s: str) -> None:
    raw = s.encode("utf-8")
    send_all(sock, struct.pack("<I", len(raw)) + raw)


def recv_str(sock: socket.socket, max_len: int | None = None) -> str:
    """Receive one length-prefixed string.  ``max_len`` (tracker-side
    handshake reads) turns an absurd length prefix — a stray client's
    bytes misread as a length — into a typed :class:`HandshakeError`
    instead of an unbounded buffering loop."""
    n = recv_u32(sock)
    if max_len is not None and n > max_len:
        raise HandshakeError(
            f"string length {n} exceeds the handshake cap {max_len}",
            parsed_magic=True)
    try:
        return recv_all(sock, n).decode("utf-8")
    except UnicodeDecodeError as e:
        if max_len is None:
            raise
        raise HandshakeError(f"non-utf-8 handshake string: {e}",
                             parsed_magic=True) from e


def send_hello(sock: socket.socket, cmd: str, task_id: str, world: int,
               job: str = DEFAULT_JOB) -> None:
    """The worker→tracker hello every fresh tracker connection opens
    with.  The default job sends the classic MAGIC layout — byte-
    identical to the pre-multi-tenant wire, so it still speaks to old
    trackers; a named job rides the MAGIC_JOB extension."""
    if job == DEFAULT_JOB:
        send_u32(sock, MAGIC)
    else:
        send_u32(sock, MAGIC_JOB)
        send_str(sock, job)
    send_str(sock, cmd)
    send_str(sock, task_id)
    send_u32(sock, world)


def recv_hello(sock: socket.socket) -> tuple[str, str, str, int]:
    """Tracker-side hardened hello parse: ``(job, cmd, task_id,
    world)``.  Raises :class:`HandshakeError` — with ``parsed_magic``
    False for a stray client (drop silently) and True once the magic
    checked out (a typed reject reply is appropriate)."""
    magic = recv_u32(sock)
    if magic == MAGIC:
        job = DEFAULT_JOB
    elif magic == MAGIC_JOB:
        job = recv_str(sock, max_len=MAX_HELLO_STR)
        if not valid_job_id(job):
            raise HandshakeError(f"invalid job id {job!r}",
                                 parsed_magic=True)
    else:
        raise HandshakeError(f"bad magic 0x{magic:08x}")
    cmd = recv_str(sock, max_len=MAX_HELLO_STR)
    task_id = recv_str(sock, max_len=MAX_HELLO_STR)
    world = recv_u32(sock)
    return job, cmd, task_id, world


@dataclass
class RejectReply:
    """Typed refusal in place of a topology reply (admission control /
    malformed handshake).  On the wire: u32 REJECT, u32 code, str
    reason."""

    code: int
    reason: str = ""

    def send(self, sock: socket.socket) -> None:
        send_u32(sock, REJECT)
        send_u32(sock, self.code)
        send_str(sock, self.reason)

    @classmethod
    def recv_tail(cls, sock: socket.socket) -> "RejectReply":
        """Read the frame after the caller consumed the REJECT u32."""
        code = recv_u32(sock)
        reason = recv_str(sock, max_len=MAX_HELLO_STR)
        return cls(code, reason)


def shard_moved_reason(generation: int, shard: int, host: str,
                       port: int) -> str:
    """The REJECT_SHARD_MOVED reason payload: enough for the rejected
    client to re-target the owning shard without another directory
    round trip (and to drop a stale cached ring older than ``gen``)."""
    return f"gen={int(generation)};shard={int(shard)};" \
           f"endpoint={host}:{int(port)}"


def parse_shard_moved(reason: str) -> tuple[int, int, str, int] | None:
    """Parse a :func:`shard_moved_reason` string into ``(generation,
    shard, host, port)``; None when the reason does not carry a
    redirect (an old or third-party tracker — the client then falls
    back to a full directory refresh)."""
    fields: dict[str, str] = {}
    for part in str(reason).split(";"):
        k, sep, v = part.partition("=")
        if sep:
            fields[k.strip()] = v.strip()
    ep = fields.get("endpoint", "")
    host, sep, port_s = ep.rpartition(":")
    if not ("gen" in fields and sep and host):
        return None
    try:
        return (int(fields["gen"]), int(fields.get("shard", -1)),
                host, int(port_s))
    except ValueError:
        return None


@dataclass
class TopologyReply:
    """What the tracker tells each worker at rendezvous."""

    rank: int
    world: int
    parent: int                      # NONE if root
    neighbors: list[int] = field(default_factory=list)
    ring_prev: int = NONE
    ring_next: int = NONE
    connect: list[tuple[int, str, int]] = field(default_factory=list)
    naccept: int = 0
    relaunched: int = 0
    epoch: int = 0
    groups: list[int] = field(default_factory=list)
    sched: str = ""                  # live schedule directive ("" = none)
    demoted: list[int] = field(default_factory=list)

    def send(self, sock: socket.socket) -> None:
        send_u32(sock, self.rank)
        send_u32(sock, self.world)
        send_u32(sock, self.parent)
        send_u32(sock, len(self.neighbors))
        for r in self.neighbors:
            send_u32(sock, r)
        send_u32(sock, self.ring_prev)
        send_u32(sock, self.ring_next)
        send_u32(sock, len(self.connect))
        for r, host, port in self.connect:
            send_u32(sock, r)
            send_str(sock, host)
            send_u32(sock, port)
        send_u32(sock, self.naccept)
        send_u32(sock, self.relaunched)
        send_u32(sock, self.epoch)
        send_u32(sock, len(self.groups))
        for g in self.groups:
            send_u32(sock, g)
        send_str(sock, self.sched)
        send_u32(sock, len(self.demoted))
        for r in self.demoted:
            send_u32(sock, r)

    @classmethod
    def recv(cls, sock: socket.socket) -> "TopologyReply":
        return cls._recv_tail(sock, recv_u32(sock))

    @classmethod
    def recv_or_reject(cls, sock: socket.socket
                       ) -> "TopologyReply | RejectReply":
        """Registration reply dispatch: the REJECT sentinel in the rank
        slot means an admission/handshake refusal frame follows."""
        first = recv_u32(sock)
        if first == REJECT:
            return RejectReply.recv_tail(sock)
        return cls._recv_tail(sock, first)

    @classmethod
    def _recv_tail(cls, sock: socket.socket, rank: int) -> "TopologyReply":
        world = recv_u32(sock)
        parent = recv_u32(sock)
        neighbors = [recv_u32(sock) for _ in range(recv_u32(sock))]
        ring_prev = recv_u32(sock)
        ring_next = recv_u32(sock)
        connect = []
        for _ in range(recv_u32(sock)):
            r = recv_u32(sock)
            host = recv_str(sock)
            port = recv_u32(sock)
            connect.append((r, host, port))
        naccept = recv_u32(sock)
        relaunched = recv_u32(sock)
        epoch = recv_u32(sock)
        groups = [recv_u32(sock) for _ in range(recv_u32(sock))]
        # Adaptive-controller trailing fields: a pre-adaptive tracker
        # sends nothing past groups and closes the one-shot socket —
        # a CLEAN EOF exactly at this boundary means "old layout",
        # default the fields.  Anything else (reset mid-field, timeout,
        # garbage length) RAISES like any other truncated reply, so the
        # registration retries instead of one rank silently running
        # without the directive its peers adopted (schedule choice is
        # a collective decision).
        sched, demoted = "", []
        n = recv_u32_or_eof(sock)
        if n is not None:
            if n > MAX_HELLO_STR:
                raise HandshakeError(
                    f"sched directive length {n} exceeds the cap",
                    parsed_magic=True)
            sched = recv_all(sock, n).decode("utf-8")
            demoted = [recv_u32(sock) for _ in range(recv_u32(sock))]
        return cls(rank, world, parent, neighbors, ring_prev, ring_next,
                   connect, naccept, relaunched, epoch, groups,
                   sched, demoted)
