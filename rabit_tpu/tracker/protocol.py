"""Wire protocol between workers and the tracker.

A fresh design (not the reference's ad-hoc handshake, though it serves the
same role — reference: src/allreduce_base.cc:138-158 ConnectTracker and
tracker/rabit_tracker.py:47-122): little-endian length-prefixed primitives
chosen so the C++ native engine can speak it with a few dozen lines and no
JSON dependency.

All integers are u32 little-endian.  Strings are u32 length + utf-8 bytes.

Worker → tracker, on every fresh tracker connection:

    u32 magic       MAGIC (protocol/version gate)
    str cmd         "start" | "recover" | "rescale" | "print" | "shutdown"
    str task_id     stable worker identity (rank reassignment on restart)
    u32 world       world size the worker was launched with (0 = unknown)

then, for cmd in {start, recover, rescale}:

    str host        worker's listening address
    u32 port        worker's listening port

tracker → worker reply (start/recover/rescale only):

    u32 rank
    u32 world
    u32 parent      tree parent rank, NONE if root
    u32 nneighbor   tree neighbor count, then that many u32 ranks
    u32 ring_prev   ring predecessor rank
    u32 ring_next   ring successor rank
    u32 nconnect    peers to actively connect: (u32 rank, str host, u32 port)*
    u32 naccept     number of inbound connections to expect
    u32 relaunched  1 iff this is a cmd=start re-registration of a task_id
                    that already completed a rendezvous round — i.e. a
                    mid-job relaunch.  Lets engines detect relaunch even
                    when the platform restarts workers with a clean
                    environment (no RABIT_NUM_TRIAL/RABIT_RELAUNCH).
    u32 epoch       the membership epoch this topology belongs to; bumped
                    every time the tracker completes a RESCALE round
                    (world grew or shrank, ranks reassigned).  Trailing
                    field on purpose: a reader of the pre-elastic layout
                    simply leaves it unread on the one-shot socket.
    u32 ngroups     host-group handout for the topology-aware schedules:
                    one group id per rank (ranks on the same host share
                    an id — or the RABIT_TRACKER_GROUPS override), then
                    that many u32 ids.  The hierarchical two-level
                    schedule keys off it (rabit_tpu/sched/hier.py).
                    Trailing like epoch: older readers leave it unread.

for cmd == "print": str message follows, no reply.
for cmd == "shutdown": nothing follows, no reply.
for cmd == "heartbeat": u32 period_ms follows, then the connection stays
    OPEN (the one persistent tracker connection) carrying one u32 beat
    per period; HEARTBEAT_BYE closes it cleanly at worker shutdown.
    EOF without the bye, or a missed-beat budget, marks the worker dead
    on the control plane (tracker/tracker.py heartbeat sweep).

Worker ↔ worker, on each data link after connect:

    u32 magic, u32 own_rank     (both directions; ranks identify links)
"""
from __future__ import annotations

import socket
import struct
from dataclasses import dataclass, field

MAGIC = 0x7AB17901
NONE = 0xFFFFFFFF

CMD_START = "start"
CMD_RECOVER = "recover"
CMD_PRINT = "print"
CMD_SHUTDOWN = "shutdown"
# "jaxsvc": rank 0 of the XLA engine asks the tracker to host a fresh
# JAX coordination service for the job's world size.  Reply: u32 port
# (0 = tracker cannot host, e.g. no jaxlib).  Hosting the service in
# the long-lived tracker decouples the device-plane coordinator from
# worker lifetimes: ANY worker's death — including rank 0's — is then a
# recoverable peer failure instead of a fatal loss of the coordination
# service.  Previous epochs' services are retained until the tracker
# closes (a degraded member may still be attached to one).
CMD_JAXSVC = "jaxsvc"
# "formbar": the formation barrier.  Each XLA-engine worker posts this
# as its LAST act before the blocking jaxlib group registration; the
# tracker replies u32 1 (proceed) only once every worker of the job has
# posted, and 0 (abort — start degraded) when any task re-registers as
# a mid-job relaunch or the barrier times out.  Needed because a client
# stuck in a doomed registration barrier cannot escape: when a
# co-registrant dies the coordination service's error push fatally
# terminates the blocked clients (jaxlib client.h:80), and the client's
# own init_timeout is routed through the same fatal path rather than
# raising.  So liveness is decided on the control plane BEFORE anyone
# blocks in the device-plane registration.
CMD_FORMBAR = "formbar"
# "heartbeat": the persistent liveness channel.  A worker opens ONE of
# these right after its first rendezvous, sends its period (u32 ms),
# then one u32 beat per period for the life of the process.  The
# tracker's deadline sweep marks a worker dead once
# rabit_heartbeat_miss periods pass without a beat — liveness is
# decided PROACTIVELY on the control plane, so a hung rank is evicted
# (and its supervisor notified) without any collective op having to
# touch it first.  A clean shutdown sends HEARTBEAT_BYE before close;
# EOF without the bye means the process died.
CMD_HEARTBEAT = "heartbeat"
HEARTBEAT_BYE = 0xFFFFFFFF
# "rescale": a current member re-registering for an elastic membership
# epoch (doc/fault_tolerance.md "Elastic membership & tracker HA").
# Same payload/reply as start/recover; the round it joins completes at
# the tracker's pending TARGET world (grown by admitted joiners, shrunk
# by heartbeat-detected deaths), ranks are reassigned deterministically
# (surviving members by old rank, then joiners by task_id) and the
# reply's epoch field is bumped.  Members enter this round together at
# a checkpoint-commit boundary (the K_RESCALE consensus bit — see
# engine/robust.py), so no in-flight collective ever spans two worlds.
CMD_RESCALE = "rescale"
# "epoch": one-shot membership poll.  u32 committed_version follows
# (the worker's current checkpoint version — the tracker journals the
# max as the job's committed progress); reply u32 epoch, u32
# target_epoch, u32 target_world.  target_epoch > epoch means a rescale
# is pending and the next commit boundary should re-rendezvous with
# cmd=rescale.  Best-effort on the worker side: an unreachable tracker
# (e.g. restarting) reads as "no change" — polling never stalls
# training.
CMD_EPOCH = "epoch"


def send_all(sock: socket.socket, data: bytes) -> None:
    sock.sendall(data)


def recv_all(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionResetError("peer closed during recv")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def send_u32(sock: socket.socket, value: int) -> None:
    send_all(sock, struct.pack("<I", value))


def recv_u32(sock: socket.socket) -> int:
    return struct.unpack("<I", recv_all(sock, 4))[0]


def send_str(sock: socket.socket, s: str) -> None:
    raw = s.encode("utf-8")
    send_all(sock, struct.pack("<I", len(raw)) + raw)


def recv_str(sock: socket.socket) -> str:
    n = recv_u32(sock)
    return recv_all(sock, n).decode("utf-8")


@dataclass
class TopologyReply:
    """What the tracker tells each worker at rendezvous."""

    rank: int
    world: int
    parent: int                      # NONE if root
    neighbors: list[int] = field(default_factory=list)
    ring_prev: int = NONE
    ring_next: int = NONE
    connect: list[tuple[int, str, int]] = field(default_factory=list)
    naccept: int = 0
    relaunched: int = 0
    epoch: int = 0
    groups: list[int] = field(default_factory=list)

    def send(self, sock: socket.socket) -> None:
        send_u32(sock, self.rank)
        send_u32(sock, self.world)
        send_u32(sock, self.parent)
        send_u32(sock, len(self.neighbors))
        for r in self.neighbors:
            send_u32(sock, r)
        send_u32(sock, self.ring_prev)
        send_u32(sock, self.ring_next)
        send_u32(sock, len(self.connect))
        for r, host, port in self.connect:
            send_u32(sock, r)
            send_str(sock, host)
            send_u32(sock, port)
        send_u32(sock, self.naccept)
        send_u32(sock, self.relaunched)
        send_u32(sock, self.epoch)
        send_u32(sock, len(self.groups))
        for g in self.groups:
            send_u32(sock, g)

    @classmethod
    def recv(cls, sock: socket.socket) -> "TopologyReply":
        rank = recv_u32(sock)
        world = recv_u32(sock)
        parent = recv_u32(sock)
        neighbors = [recv_u32(sock) for _ in range(recv_u32(sock))]
        ring_prev = recv_u32(sock)
        ring_next = recv_u32(sock)
        connect = []
        for _ in range(recv_u32(sock)):
            r = recv_u32(sock)
            host = recv_str(sock)
            port = recv_u32(sock)
            connect.append((r, host, port))
        naccept = recv_u32(sock)
        relaunched = recv_u32(sock)
        epoch = recv_u32(sock)
        groups = [recv_u32(sock) for _ in range(recv_u32(sock))]
        return cls(rank, world, parent, neighbors, ring_prev, ring_next,
                   connect, naccept, relaunched, epoch, groups)
