"""Hash-partitioned job directory for the sharded tracker control plane.

ROADMAP item 4: the one ``Tracker`` process is the control plane's
scalability ceiling and its single point of coordinated failure.  This
module splits the job table across **N tracker shards** behind a small
directory service (doc/fault_tolerance.md "Sharded tracker"):

* :class:`HashRing` — a consistent-hash ring mapping job ids to shard
  indices.  The ring is a PURE function of the live shard set (plus the
  vnode count), so the directory, every shard, and every client build
  the identical ring from the same membership snapshot — no ring state
  ever crosses the wire, only membership.
* :class:`Directory` — the in-process membership authority: live
  shards, an explicit **generation** number bumped on every membership
  change, per-shard load reports for fleet-wide admission accounting,
  and the ``--max-jobs``/``--max-total-workers`` caps.  With a
  :class:`~rabit_tpu.tracker.replica.MembershipJournal` attached, every
  membership change is journaled — the replication substrate.
* :class:`DirectoryServer` — serves the directory over HTTP (stdlib
  ``ThreadingHTTPServer``; JSON bodies) plus the **hierarchical obs
  fold**: its ``/status`` and ``/metrics`` scrape every live shard's
  obs endpoint and merge them (``obs.export.merge_status_docs`` /
  ``merge_prometheus_pages``).  A health-monitor thread probes shard
  ``/healthz``; a shard that misses its budget is removed, bumping the
  generation so the ring reassigns its jobs to survivors (which then
  journal-replay them — see ``shard.py``).

  **Replication** (ISSUE 19): run N ``DirectoryServer`` replicas, each
  with a ``--replica-index`` and the full ``--peers`` URL list.  The
  LOWEST healthy replica id leads (deterministic lease — no vote);
  followers mirror the leader's membership journal over
  ``GET /journal`` and serve read-only cached snapshots, so reads
  survive any replica's death instantly.  Writes landing on a follower
  get a typed ``not_leader`` redirect.  On leader death the next id
  detects ``lease_miss`` consecutive probe misses (≈ one lease
  interval), replays its journal copy, and takes over at a generation
  bumped PAST the highest it ever observed — fencing any snapshot the
  dead leader handed out.  A directory SIGKILL therefore costs at most
  one lease interval of registration latency, never a job.
* :class:`DirectoryClient` — the cached client side.  Accepts one base
  URL or a comma-separated replica list; reads rotate across replicas
  on connection failure, writes follow ``not_leader`` redirects to the
  current leader.  Consumers hold a snapshot + locally-built ring and
  go back to the wire only on a miss, an explicit
  :meth:`DirectoryClient.invalidate` (driven by a ``REJECT_SHARD_MOVED``
  redirect carrying a newer generation), or a refresh interval.  A
  directory OUTAGE is ridden on the cached snapshot with ONE warning
  per episode (rate-limited degradation — never a warning per poll
  tick, never a stall).

The directory processes are deliberately SEPARATE from the shards they
index: killing a shard can never take the membership authority with
it.  Every shard additionally mirrors the latest snapshot on its own
obs endpoint (``GET /directory``) so clients can bootstrap from any
shard they already know.
"""
from __future__ import annotations

import argparse
import bisect
import hashlib
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from rabit_tpu import chaos as chaos_mod
from rabit_tpu.obs import export as obs_export
from rabit_tpu.tracker.replica import (EV_REGISTER, EV_REMOVE,
                                       EV_TAKEOVER, LeaseState,
                                       MembershipJournal, parse_peers)
from rabit_tpu.utils.checks import log

# Vnodes per shard on the ring.  64 keeps the moved-job fraction on a
# membership change near the ideal 1/N at single-digit shard counts
# while the full ring stays a few hundred points — rebuild is free.
DEFAULT_VNODES = 64
DEFAULT_PORT = 9400
DEFAULT_HEALTH_SEC = 1.0
DEFAULT_HEALTH_MISS = 5
DEFAULT_LEASE_SEC = 0.5
DEFAULT_LEASE_MISS = 3
_HTTP_TIMEOUT = 5.0
# Write redirect bound: a not_leader reply names the current leader;
# chasing more than this many hops means the lease is mid-flip — the
# caller's retry budget (shard poll cadence, engine backoff walk)
# absorbs the window instead.
_MAX_LEADER_HOPS = 3


def _ring_hash(key: str) -> int:
    """64-bit ring point.  md5 rather than crc32: crc32 is linear, so
    names differing only in a trailing character land in correlated
    positions — a tenant fleet named job0..jobN can pile onto ONE
    shard.  md5's avalanche gives near-uniform arcs and spreads
    sequential names; cryptographic strength is irrelevant here, only
    determinism across processes (hashlib is seed-stable, unlike
    ``hash()``)."""
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over shard indices.

    Points are ``md5("shard<idx>:<vnode>")`` — deterministic from the
    (sorted) shard set alone, so two parties holding the same
    membership snapshot agree on every job's owner without exchanging
    the ring itself.  Adding or removing one shard moves only the jobs
    whose arc changed hands (~1/N of them), which is exactly what keeps
    a shard handoff a bounded replay instead of a fleet-wide reshuffle
    (pinned by tests/test_shard.py)."""

    def __init__(self, shards, vnodes: int = DEFAULT_VNODES) -> None:
        self._vnodes = max(int(vnodes), 1)
        points: list[tuple[int, int]] = []
        for idx in sorted({int(s) for s in shards}):
            for v in range(self._vnodes):
                points.append((_ring_hash(f"shard{idx}:{v}"), idx))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [i for _, i in points]

    def __len__(self) -> int:
        return len({i for i in self._owners})

    def owner(self, job: str) -> int:
        """Ring owner of ``job`` (first point clockwise of its hash).
        Raises :class:`LookupError` on an empty ring — the caller turns
        that into a retryable condition, not a default shard."""
        if not self._hashes:
            raise LookupError("hash ring is empty (no live shards)")
        pos = bisect.bisect_left(self._hashes, _ring_hash(str(job)))
        if pos == len(self._hashes):
            pos = 0
        return self._owners[pos]


class Directory:
    """In-process membership authority (one per fleet; one per replica
    when replicated — the leader's is authoritative, followers hold a
    journal-mirrored read-only copy).

    Tracks live shards, their endpoints and last load report, the caps,
    and the **generation** — bumped on every membership change (shard
    registered at a new endpoint, shard removed) and NEVER on load
    reports, so cached rings stay valid exactly as long as membership
    does.  All methods are lock-guarded; :meth:`snapshot` is the only
    thing that crosses the wire.  With ``journal`` attached, every
    generation-bumping change appends one membership event."""

    def __init__(self, max_jobs: int = 0, max_total_workers: int = 0,
                 vnodes: int = DEFAULT_VNODES,
                 journal: MembershipJournal | None = None) -> None:
        self._lock = threading.RLock()
        self._shards: dict[int, dict] = {}
        self._generation = 0
        self._max_jobs = int(max_jobs)
        self._max_total_workers = int(max_total_workers)
        self._vnodes = int(vnodes)
        self._ring = HashRing([], self._vnodes)
        self.journal = journal

    def _journal_event(self, event: dict) -> None:
        if self.journal is not None:
            self.journal.append(event)

    # -- membership ---------------------------------------------------
    def register(self, index: int, host: str, port: int,
                 obs_port: int = 0) -> dict:
        """Add (or re-register) a shard.  Idempotent for an unchanged
        endpoint — a shard's periodic re-register never churns the
        generation; a NEW index or a moved endpoint bumps it."""
        index = int(index)
        with self._lock:
            row = self._shards.get(index)
            endpoint = (str(host), int(port), int(obs_port))
            if row is None or (row["host"], row["port"],
                               row["obs_port"]) != endpoint:
                self._shards[index] = {
                    "host": str(host), "port": int(port),
                    "obs_port": int(obs_port),
                    "jobs": 0, "workers": 0, "ts": time.monotonic(),
                }
                self._generation += 1
                self._ring = HashRing(self._shards, self._vnodes)
                self._journal_event({
                    "ev": EV_REGISTER, "gen": self._generation,
                    "index": index, "host": str(host),
                    "port": int(port), "obs_port": int(obs_port),
                    "ts": time.time()})
                log("directory: shard %d @ %s:%d registered (gen %d)",
                    index, host, int(port), self._generation)
            else:
                row["ts"] = time.monotonic()
            return self._snapshot_locked()

    def remove(self, index: int, by: str = "health") -> bool:
        """Drop a shard (health monitor or operator).  Bumps the
        generation so survivors adopt the dead shard's arc."""
        with self._lock:
            if int(index) not in self._shards:
                return False
            del self._shards[int(index)]
            self._generation += 1
            self._ring = HashRing(self._shards, self._vnodes)
            self._journal_event({
                "ev": EV_REMOVE, "gen": self._generation,
                "index": int(index), "by": str(by), "ts": time.time()})
            log("directory: shard %d removed (gen %d, %d left)",
                int(index), self._generation, len(self._shards))
            return True

    def poll(self, index: int, jobs: int = 0, workers: int = 0) -> dict:
        """A shard's periodic load report (doubles as its liveness
        beat).  Returns the snapshot so one round trip both reports and
        learns the current generation + fleet totals."""
        with self._lock:
            row = self._shards.get(int(index))
            if row is not None:
                row["jobs"] = max(int(jobs), 0)
                row["workers"] = max(int(workers), 0)
                row["ts"] = time.monotonic()
            return self._snapshot_locked()

    # -- replication hooks --------------------------------------------
    def apply_event(self, ev: dict) -> None:
        """Fold ONE mirrored membership event into this (follower)
        replica — never re-journaled here; the sync loop appends its
        own copy.  Generations only move forward."""
        kind = ev.get("ev")
        with self._lock:
            try:
                gen = int(ev.get("gen", 0))
                if kind == EV_REGISTER:
                    idx = int(ev["index"])
                    old = self._shards.get(idx)
                    self._shards[idx] = {
                        "host": str(ev["host"]), "port": int(ev["port"]),
                        "obs_port": int(ev.get("obs_port", 0)),
                        "jobs": (old or {}).get("jobs", 0),
                        "workers": (old or {}).get("workers", 0),
                        "ts": time.monotonic()}
                elif kind == EV_REMOVE:
                    self._shards.pop(int(ev["index"]), None)
                elif kind != EV_TAKEOVER:
                    return
            except (KeyError, TypeError, ValueError):
                return
            self._generation = max(self._generation, gen)
            self._ring = HashRing(self._shards, self._vnodes)

    def install(self, generation: int, shards: dict[int, dict]) -> None:
        """Bulk-install a journal fold (leader takeover / restart).
        The generation only moves forward — a replayed prefix can
        never rewind what a live fleet already adopted."""
        with self._lock:
            self._generation = max(self._generation, int(generation))
            self._shards = {
                int(i): {"host": row["host"], "port": int(row["port"]),
                         "obs_port": int(row.get("obs_port", 0)),
                         "jobs": 0, "workers": 0,
                         "ts": time.monotonic()}
                for i, row in shards.items()}
            self._ring = HashRing(self._shards, self._vnodes)

    def takeover(self, replica: int, dead: list[int],
                 observed_gen: int = 0) -> int:
        """Fence a leader takeover: bump the generation past both this
        replica's journal AND the highest generation it ever observed
        from any peer, and journal the takeover naming the dead
        replica(s) — the postmortem coordinate.  Returns the new
        generation."""
        with self._lock:
            self._generation = max(self._generation,
                                   int(observed_gen)) + 1
            gen = self._generation
        self._journal_event({
            "ev": EV_TAKEOVER, "gen": gen, "replica": int(replica),
            "dead": sorted(int(d) for d in dead), "ts": time.time()})
        log("directory: replica %d took over at generation %d "
            "(dead replica(s): %s)", replica, gen, sorted(dead))
        return gen

    # -- queries ------------------------------------------------------
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def owner(self, job: str):
        """``(index, host, port)`` of the job's ring owner, or None on
        an empty fleet."""
        with self._lock:
            try:
                idx = self._ring.owner(job)
            except LookupError:
                return None
            row = self._shards[idx]
            return (idx, row["host"], row["port"])

    def snapshot(self) -> dict:
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        return {
            "generation": self._generation,
            "vnodes": self._vnodes,
            "shards": [{"index": i, "host": r["host"], "port": r["port"],
                        "obs_port": r["obs_port"], "jobs": r["jobs"],
                        "workers": r["workers"]}
                       for i, r in sorted(self._shards.items())],
            "caps": {"max_jobs": self._max_jobs,
                     "max_total_workers": self._max_total_workers},
            "fleet": {"jobs": sum(r["jobs"]
                                  for r in self._shards.values()),
                      "workers": sum(r["workers"]
                                     for r in self._shards.values())},
        }

    def stale(self, budget_sec: float) -> list[int]:
        """Shard indices whose last beat (register/poll) is older than
        ``budget_sec`` — candidates for the health monitor's probe."""
        now = time.monotonic()
        with self._lock:
            return [i for i, r in self._shards.items()
                    if now - r["ts"] > budget_sec]


def ring_from_snapshot(snap: dict) -> HashRing:
    """Rebuild the ring a snapshot implies — the shared client/shard
    path, so everyone hashes identically by construction."""
    return HashRing((s["index"] for s in snap.get("shards", ())),
                    int(snap.get("vnodes", DEFAULT_VNODES)))


def _http_json(url: str, payload: dict | None = None,
               timeout: float = _HTTP_TIMEOUT):
    """One JSON round trip (GET, or POST when ``payload`` given)."""
    data = None
    headers = {}
    if payload is not None:
        data = json.dumps(payload).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


class DirectoryClient:
    """Cached client over one or more :class:`DirectoryServer` replicas
    (or any endpoint mirroring ``GET /directory`` — every shard does).

    ``base_url`` may be a single URL or a comma-separated replica list
    (index == replica id).  Reads rotate to the next replica on a
    connection failure; writes additionally follow the typed
    ``not_leader`` redirect to the current leader (bounded hops).

    Owner lookups hit the local ring; the wire is touched only on
    first use, after :meth:`invalidate` (a ``REJECT_SHARD_MOVED``
    redirect told us our generation is stale), or when ``max_age_sec``
    has passed — so the steady-state rendezvous path costs zero
    directory round trips.  A refresh that fails WITH a cached
    snapshot in hand rides the cache (bounded staleness beats a
    stall) and warns exactly once per outage episode."""

    def __init__(self, base_url: str, timeout: float = _HTTP_TIMEOUT,
                 max_age_sec: float = 30.0) -> None:
        self._bases = parse_peers(base_url)
        if not self._bases:
            raise ValueError(f"empty directory url: {base_url!r}")
        self._timeout = float(timeout)
        self._max_age = float(max_age_sec)
        self._lock = threading.Lock()
        self._snap: dict | None = None
        self._ring: HashRing | None = None
        self._fetched = 0.0
        self._active = 0          # current replica (rotates on failure)
        self._chaos = None        # ChaosPlan for the dir_* link sites
        # Degradation-path rate limit (one warning per outage episode,
        # pinned by tests/test_replica.py): stale_rides counts every
        # refresh failure ridden on the cache; stale_warnings counts
        # the log lines actually emitted.
        self._stale_episode = False
        self.stale_rides = 0
        self.stale_warnings = 0

    @property
    def base_url(self) -> str:
        """The full (possibly comma-separated) endpoint spec — what a
        launcher hands workers so they see every replica too."""
        return ",".join(self._bases)

    @property
    def generation(self) -> int:
        with self._lock:
            return int(self._snap["generation"]) if self._snap else -1

    def attach_chaos(self, plan) -> None:
        """Arm the seeded fault plan at the directory link sites
        (``dir_register`` / ``dir_poll`` / ``dir_resolve``).  Only
        rules naming those sites ever fire — per-rule consult counters
        keep every other site's schedule untouched."""
        self._chaos = plan

    def _chaos_link(self, site: str) -> None:
        if self._chaos is not None:
            kind = self._chaos.link(site)
            if kind == chaos_mod.KIND_RESET:
                raise ConnectionResetError(
                    f"[chaos] injected {site} reset")

    # -- wire ---------------------------------------------------------
    def _request(self, path: str, payload: dict | None = None):
        """One logical round trip across the replica set: start at the
        active replica, rotate on connection failure; a ``not_leader``
        reply re-targets the named leader (writes only reach one).
        Raises the LAST failure once every replica and hop is spent —
        callers ride their existing retry budgets."""
        last: Exception | None = None
        hops = 0
        with self._lock:
            start, n = self._active, len(self._bases)
        url_override: str | None = None
        for attempt in range(n + _MAX_LEADER_HOPS):
            if url_override is not None:
                url, url_override = url_override, None
            else:
                idx = (start + attempt) % n
                url = self._bases[idx]
            try:
                doc = _http_json(url + path, payload,
                                 timeout=self._timeout)
            except (OSError, urllib.error.URLError, ValueError) as e:
                last = e
                continue
            if isinstance(doc, dict) and doc.get("not_leader"):
                hops += 1
                if hops > _MAX_LEADER_HOPS:
                    last = OSError(
                        f"directory leader unsettled after {hops} "
                        f"redirect hop(s) (last at {url})")
                    break
                leader_url = doc.get("leader_url")
                leader = doc.get("leader")
                if isinstance(leader_url, str) and leader_url:
                    url_override = leader_url.rstrip("/")
                elif isinstance(leader, int) \
                        and 0 <= leader < len(self._bases):
                    url_override = self._bases[leader]
                # else: leader unknown mid-failover — rotate onward
                continue
            with self._lock:
                if url in self._bases:
                    self._active = self._bases.index(url)
            return doc
        if isinstance(last, Exception):
            raise last if isinstance(last, OSError) else OSError(
                f"directory request {path} failed: {last}")
        raise OSError(f"directory request {path} failed")

    def _adopt(self, snap: dict) -> dict:
        with self._lock:
            if (self._snap is None
                    or snap.get("generation", -1)
                    >= self._snap.get("generation", -1)):
                self._snap = snap
                self._ring = ring_from_snapshot(snap)
                self._fetched = time.monotonic()
            if self._stale_episode:
                self._stale_episode = False
                log("directory: refresh recovered (generation %s) — "
                    "leaving the cached snapshot",
                    snap.get("generation"))
            return self._snap

    def refresh(self) -> dict:
        """Fetch the authoritative snapshot now (raises ``OSError`` /
        ``urllib.error.URLError`` when every replica is unreachable —
        callers ride their existing retry budgets)."""
        self._chaos_link(chaos_mod.SITE_DIR_RESOLVE)
        return self._adopt(self._request("/directory"))

    def invalidate(self, min_generation: int = -1) -> None:
        """Drop the cache if it is older than ``min_generation`` (from
        a redirect reason); the next lookup refreshes."""
        with self._lock:
            if (self._snap is None or min_generation < 0
                    or self._snap.get("generation", -1) < min_generation):
                self._snap = None
                self._ring = None

    def snapshot(self, refresh: bool = False) -> dict:
        with self._lock:
            snap, age = self._snap, time.monotonic() - self._fetched
        if snap is None or refresh or age > self._max_age:
            try:
                snap = self.refresh()
            except (OSError, urllib.error.URLError, ValueError):
                if snap is None:
                    raise
                # Directory outage with a snapshot in hand: ride it.
                # One obs-visible warning per EPISODE — a worker
                # polling through a long outage must not turn the log
                # into a warning-per-tick firehose (ISSUE 19).
                with self._lock:
                    self.stale_rides += 1
                    first = not self._stale_episode
                    self._stale_episode = True
                    if first:
                        self.stale_warnings += 1
                if first:
                    log("directory: refresh failed; riding the cached "
                        "snapshot (generation %s) until the directory "
                        "answers again (warned once per outage)",
                        snap.get("generation"))
        return snap

    def owner(self, job: str):
        """``(index, host, port)`` of the job's owner per the cached
        ring (refreshing as needed), or None while the fleet is empty."""
        snap = self.snapshot()
        with self._lock:
            ring = self._ring
        if ring is None:
            return None
        try:
            idx = ring.owner(job)
        except LookupError:
            return None
        for s in snap.get("shards", ()):
            if s["index"] == idx:
                return (idx, s["host"], s["port"])
        return None

    def register(self, index: int, host: str, port: int,
                 obs_port: int = 0) -> dict:
        self._chaos_link(chaos_mod.SITE_DIR_REGISTER)
        return self._adopt(self._request(
            "/register",
            {"index": int(index), "host": host, "port": int(port),
             "obs_port": int(obs_port)}))

    def poll(self, index: int, jobs: int = 0, workers: int = 0) -> dict:
        self._chaos_link(chaos_mod.SITE_DIR_POLL)
        return self._adopt(self._request(
            "/poll",
            {"index": int(index), "jobs": int(jobs),
             "workers": int(workers)}))


class DirectoryServer:
    """HTTP face of a :class:`Directory` plus the thin global obs
    aggregator, the shard health monitor, and (when ``peers`` are
    given) one member of the replicated directory.

    Endpoints: ``GET /directory`` (snapshot), ``POST /register``,
    ``POST /poll`` (load report, returns snapshot), ``GET /healthz``,
    ``GET /replica`` (lease probe: replica id, leadership,
    generation), ``GET /journal?since=N`` (membership-event tail for
    follower sync), and the hierarchical fold — ``GET /status`` /
    ``GET /metrics`` scrape every live shard's obs endpoint and merge,
    so ``rabit_top`` pointed at any replica sees the whole fleet with
    per-job shard attribution.  Scrapes consult the chaos plan at the
    ``scrape`` site (reset/stall), and every injected fault surfaces
    as a counted failed scrape — the injected↔detected pairing the
    soak gate checks.

    Replication: the lowest healthy replica id leads.  Only the leader
    mutates membership (register/poll/health removals + journal
    appends); followers mirror the journal, serve reads, and answer
    writes with a typed ``not_leader`` redirect naming the leader.
    One replica loop per process handles both halves: probe lower ids
    (the lease) and sync from the leader (when following)."""

    def __init__(self, directory: Directory, host: str = "127.0.0.1",
                 port: int = 0,
                 health_sec: float = DEFAULT_HEALTH_SEC,
                 health_miss: int = DEFAULT_HEALTH_MISS,
                 replica_index: int = 0,
                 peers: list[str] | str | None = None,
                 lease_sec: float = DEFAULT_LEASE_SEC,
                 lease_miss: int = DEFAULT_LEASE_MISS) -> None:
        self._dir = directory
        self._health_sec = float(health_sec)
        self._health_miss = max(int(health_miss), 1)
        self._miss: dict[int, int] = {}
        self._stop = threading.Event()
        self._counters = {"scrapes": 0, "scrape_failures": 0,
                          "chaos.injected": 0, "shards_removed": 0}
        self._clock = threading.Lock()
        self.replica_index = int(replica_index)
        self._peers = (parse_peers(peers) if isinstance(peers, str)
                       else list(peers or []))
        self._lease_sec = max(float(lease_sec), 0.05)
        self._lease = LeaseState(self.replica_index,
                                 max(int(lease_miss), 1))
        # Replica 0 (and the unreplicated singleton) leads from birth;
        # higher ids must first see every lower id miss its budget.
        self._leading = self._lease.is_leader()
        self._sync_cursor: dict[int, int] = {}   # leader id -> last seq
        self._chaos = chaos_mod.configure(
            {}, identity=f"directory{self.replica_index}")
        if self._leading:
            # Leader bootstrap doubles as the RESTART path: a replica
            # coming back over an existing journal resumes at (not
            # below) the generation it last handed out.
            self._bootstrap_from_journal()
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet the stdlib
                pass

            def _reply(self, body: bytes, ctype: str,
                       code: int = 200) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, doc, code: int = 200) -> None:
                self._reply(json.dumps(doc, sort_keys=True).encode(),
                            "application/json", code)

            def do_GET(self):
                try:
                    parsed = urllib.parse.urlsplit(self.path)
                    path = parsed.path
                    if path == "/directory":
                        self._json(server._dir.snapshot())
                    elif path == "/replica":
                        self._json(server.replica_doc())
                    elif path == "/journal":
                        q = urllib.parse.parse_qs(parsed.query)
                        since = int((q.get("since") or ["0"])[0])
                        self._json(server.journal_doc(since))
                    elif path == "/status":
                        self._json(server.merged_status())
                    elif path == "/metrics":
                        self._reply(server.merged_metrics().encode(),
                                    "text/plain; version=0.0.4")
                    elif path in ("/", "/healthz"):
                        self._reply(b"ok\n", "text/plain")
                    else:
                        self.send_error(404)
                except Exception as e:  # noqa: BLE001 — serve thread
                    log("directory: GET %s failed: %s", self.path, e)
                    try:
                        self.send_error(500)
                    except OSError as e2:
                        log("directory: 500 reply failed: %s", e2)

            def do_POST(self):
                try:
                    path = self.path.split("?")[0]
                    n = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if path not in ("/register", "/poll"):
                        self.send_error(404)
                        return
                    if not server.is_leader():
                        # Typed write redirect: followers are
                        # read-only replicas by contract.
                        self._json(server.not_leader_doc())
                        return
                    if path == "/register":
                        self._json(server._dir.register(
                            body["index"], body.get("host", "127.0.0.1"),
                            body["port"], body.get("obs_port", 0)))
                    else:
                        self._json(server._dir.poll(
                            body["index"], body.get("jobs", 0),
                            body.get("workers", 0)))
                except Exception as e:  # noqa: BLE001 — serve thread
                    log("directory: POST %s failed: %s", self.path, e)
                    try:
                        self.send_error(500)
                    except OSError as e2:
                        log("directory: 500 reply failed: %s", e2)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._threads = [
            threading.Thread(target=self._httpd.serve_forever,
                             name="rabit-directory-http", daemon=True),
            threading.Thread(target=self._health_loop,
                             name="rabit-directory-health", daemon=True),
        ]
        if self._peers:
            self._threads.append(threading.Thread(
                target=self._replica_loop,
                name=f"rabit-directory-r{self.replica_index}",
                daemon=True))

    def start(self) -> "DirectoryServer":
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    def _count(self, name: str, n: int = 1) -> None:
        with self._clock:
            self._counters[name] = self._counters.get(name, 0) + n

    # -- replication ---------------------------------------------------
    def is_leader(self) -> bool:
        return self._leading

    def replica_doc(self) -> dict:
        return {"replica": self.replica_index,
                "leader": self._leading,
                "generation": self._dir.generation}

    def journal_doc(self, since: int = 0) -> dict:
        j = self._dir.journal
        if j is None:
            return {"seq": 0, "events": []}
        return {"seq": j.seq, "events": j.since(int(since))}

    def not_leader_doc(self) -> dict:
        leader = None
        healthy = self._lease.healthy_lower()
        if healthy:
            leader = healthy[0]
        doc: dict = {"not_leader": True, "replica": self.replica_index,
                     "generation": self._dir.generation}
        if leader is not None:
            doc["leader"] = leader
            if leader < len(self._peers):
                doc["leader_url"] = self._peers[leader]
        return doc

    def _bootstrap_from_journal(self) -> None:
        j = self._dir.journal
        if j is None:
            return
        gen, shards = j.replay()
        if gen or shards:
            self._dir.install(gen, shards)
            log("directory: replica %d replayed %d membership "
                "event(s) -> generation %d, %d shard(s)",
                self.replica_index, j.seq, self._dir.generation,
                len(shards))

    def _probe_replica(self, peer: int) -> None:
        url = self._peers[peer] + "/replica"
        try:
            with urllib.request.urlopen(
                    url, timeout=max(self._lease_sec, 2.0)) as r:
                doc = json.loads(r.read().decode())
            self._lease.probe_result(
                peer, True, int(doc.get("generation", -1)))
        except (OSError, urllib.error.URLError, ValueError):
            self._lease.probe_result(peer, False)

    def _sync_from_leader(self) -> None:
        """Mirror the leader's membership-journal tail into this
        follower (events re-stamped into the local journal, applied to
        the local Directory).  Leadership changes restart the cursor —
        re-applied events are fold-idempotent by construction."""
        healthy = self._lease.healthy_lower()
        if not healthy:
            return
        leader = healthy[0]
        if leader >= len(self._peers):
            return
        cursor = self._sync_cursor.get(leader, 0)
        url = (self._peers[leader]
               + f"/journal?since={cursor}")
        try:
            with urllib.request.urlopen(
                    url, timeout=max(self._lease_sec, 2.0)) as r:
                doc = json.loads(r.read().decode())
        except (OSError, urllib.error.URLError, ValueError) as e:
            self._count("sync_failures")
            log("directory: replica %d journal sync from %d failed: %s",
                self.replica_index, leader, e)
            return
        events = doc.get("events") or []
        for ev in events:
            if not isinstance(ev, dict):
                continue
            self._sync_cursor[leader] = max(
                self._sync_cursor.get(leader, 0),
                int(ev.get("seq", 0)))
            self._dir.apply_event(ev)
            if self._dir.journal is not None:
                self._dir.journal.append(
                    {k: v for k, v in ev.items() if k != "seq"})
        if events:
            self._count("sync_events", len(events))

    def _become_leader(self) -> None:
        dead = self._lease.dead_lower()
        self._bootstrap_from_journal()
        gen = self._dir.takeover(self.replica_index, dead,
                                 self._lease.observed_gen)
        self._count("takeovers")
        log("directory: replica %d is now the leader (generation %d)",
            self.replica_index, gen)

    def _replica_loop(self) -> None:
        """One loop, both halves of replication: probe every lower id
        (the lease), then either take/keep the lead or sync from the
        leader.  Leadership is re-derived every interval, so a deposed
        leader (a lower id back up) steps down within one interval."""
        while not self._stop.wait(self._lease_sec):
            for peer in range(self.replica_index):
                self._probe_replica(peer)
            leading = self._lease.is_leader()
            if leading and not self._leading:
                self._become_leader()
            elif not leading and self._leading \
                    and self.replica_index > 0:
                log("directory: replica %d stepping down (lower "
                    "replica healthy again)", self.replica_index)
            self._leading = leading or self.replica_index == 0
            if not self._leading:
                self._sync_from_leader()

    # -- hierarchical obs fold ---------------------------------------
    def _scrape(self, url: str) -> str | None:
        """One shard obs-endpoint scrape, chaos-armed at the ``scrape``
        site.  Every failure (injected or organic) is counted, never
        raised — the fold degrades to the shards that answered."""
        self._count("scrapes")
        try:
            if self._chaos is not None:
                kind = self._chaos.link(chaos_mod.SITE_SCRAPE)
                if kind == chaos_mod.KIND_RESET:
                    self._count("chaos.injected")
                    raise ConnectionResetError("chaos: scrape reset")
            with urllib.request.urlopen(url, timeout=_HTTP_TIMEOUT) as r:
                return r.read().decode()
        except (OSError, urllib.error.URLError, ValueError) as e:
            self._count("scrape_failures")
            log("directory: scrape %s failed: %s", url, e)
            return None

    def _obs_targets(self) -> list[tuple[int, str]]:
        return [(s["index"], f"http://{s['host']}:{s['obs_port']}")
                for s in self._dir.snapshot()["shards"]
                if s.get("obs_port")]

    def merged_status(self) -> dict:
        docs = []
        for idx, base in self._obs_targets():
            text = self._scrape(base + "/status")
            if text is None:
                continue
            try:
                doc = json.loads(text)
            except ValueError:
                self._count("scrape_failures")
                continue
            if isinstance(doc, dict):
                doc.setdefault("shard", idx)
            docs.append(doc)
        out = obs_export.merge_status_docs(docs)
        out["directory"] = self._self_status()
        return out

    def merged_metrics(self) -> str:
        pages = []
        for _idx, base in self._obs_targets():
            text = self._scrape(base + "/metrics")
            if text is not None:
                pages.append(text)
        pages.append(self._self_metrics())
        return obs_export.merge_prometheus_pages(pages)

    def _self_status(self) -> dict:
        snap = self._dir.snapshot()
        with self._clock:
            counters = dict(self._counters)
        doc = {"generation": snap["generation"],
               "shards": [s["index"] for s in snap["shards"]],
               "fleet": snap["fleet"], "caps": snap["caps"],
               "counters": counters,
               "replica": self.replica_index,
               "leader": self._leading}
        j = self._dir.journal
        if j is not None:
            takeovers = [ev for ev in j.events()
                         if ev.get("ev") == EV_TAKEOVER]
            if takeovers:
                doc["takeovers"] = takeovers[-8:]
        return doc

    def _self_metrics(self) -> str:
        snap = self._dir.snapshot()
        with self._clock:
            counters = dict(self._counters)
        rlab = {"replica": str(self.replica_index)}
        samples = [("rabit_directory_generation", {},
                    snap["generation"]),
                   ("rabit_directory_shards", {}, len(snap["shards"])),
                   ("rabit_directory_fleet_jobs", {},
                    snap["fleet"]["jobs"]),
                   ("rabit_directory_fleet_workers", {},
                    snap["fleet"]["workers"]),
                   ("rabit_directory_leader", rlab,
                    1 if self._leading else 0)]
        types = {"rabit_directory_generation": "counter",
                 "rabit_directory_leader": "gauge"}
        for name, v in sorted(counters.items()):
            series = "rabit_directory_" + name.replace(".", "_")
            samples.append((series, rlab, v))
            types[series] = "counter"
        return obs_export.prometheus_text(samples, types)

    # -- health monitor ----------------------------------------------
    def _health_loop(self) -> None:
        """Probe each shard's ``/healthz`` every ``health_sec``; after
        ``health_miss`` consecutive misses the shard is removed — the
        generation bump that starts the handoff choreography.  Only
        the LEADER removes (a follower's independent verdicts would
        race the authority's)."""
        while not self._stop.wait(self._health_sec):
            if not self._leading:
                self._miss.clear()
                continue
            for s in self._dir.snapshot()["shards"]:
                idx = s["index"]
                if not s.get("obs_port"):
                    continue  # not probeable; rely on poll staleness
                url = f"http://{s['host']}:{s['obs_port']}/healthz"
                try:
                    with urllib.request.urlopen(url, timeout=2.0) as r:
                        r.read()
                    self._miss[idx] = 0
                except (OSError, urllib.error.URLError):
                    self._miss[idx] = self._miss.get(idx, 0) + 1
                    if self._miss[idx] >= self._health_miss:
                        if self._dir.remove(idx):
                            self._count("shards_removed")
                        self._miss.pop(idx, None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rabit_tpu.tracker.directory",
        description="Job directory / global obs aggregator for the "
                    "sharded tracker control plane.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT,
                    help="directory port (0 = ephemeral)")
    ap.add_argument("--max-jobs", type=int, default=0,
                    help="fleet-wide concurrent-job cap (0 = unlimited)")
    ap.add_argument("--max-total-workers", type=int, default=0,
                    help="fleet-wide worker-sum cap (0 = unlimited)")
    ap.add_argument("--vnodes", type=int, default=DEFAULT_VNODES)
    ap.add_argument("--health-sec", type=float,
                    default=DEFAULT_HEALTH_SEC)
    ap.add_argument("--health-miss", type=int,
                    default=DEFAULT_HEALTH_MISS)
    ap.add_argument("--replica-index", type=int, default=0,
                    help="this replica's id in the replica set (the "
                         "lowest healthy id leads)")
    ap.add_argument("--peers", default="",
                    help="comma-separated base URLs of ALL replicas, "
                         "index-aligned with --replica-index")
    ap.add_argument("--lease-sec", type=float, default=DEFAULT_LEASE_SEC,
                    help="leader-lease probe interval; a dead leader "
                         "is detected after --lease-miss missed probes")
    ap.add_argument("--lease-miss", type=int, default=DEFAULT_LEASE_MISS)
    ap.add_argument("--state-dir", default=None,
                    help="persist the membership journal here "
                         "(directory.r<i>.journal.jsonl); a restarted "
                         "replica replays it, resuming at (never "
                         "below) its last generation")
    args = ap.parse_args(argv)
    journal = None
    if args.state_dir:
        os.makedirs(args.state_dir, exist_ok=True)
        journal = MembershipJournal(os.path.join(
            args.state_dir,
            f"directory.r{args.replica_index}.journal.jsonl"))
    directory = Directory(max_jobs=args.max_jobs,
                          max_total_workers=args.max_total_workers,
                          vnodes=args.vnodes, journal=journal)
    server = DirectoryServer(directory, host=args.host, port=args.port,
                             health_sec=args.health_sec,
                             health_miss=args.health_miss,
                             replica_index=args.replica_index,
                             peers=args.peers,
                             lease_sec=args.lease_sec,
                             lease_miss=args.lease_miss).start()
    sys.stderr.write(
        f"directory replica {args.replica_index} listening on "
        f"{server.host}:{server.port}\n")
    sys.stderr.flush()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
