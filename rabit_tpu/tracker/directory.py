"""Hash-partitioned job directory for the sharded tracker control plane.

ROADMAP item 4: the one ``Tracker`` process is the control plane's
scalability ceiling and its single point of coordinated failure.  This
module splits the job table across **N tracker shards** behind a small
directory service (doc/fault_tolerance.md "Sharded tracker"):

* :class:`HashRing` — a consistent-hash ring mapping job ids to shard
  indices.  The ring is a PURE function of the live shard set (plus the
  vnode count), so the directory, every shard, and every client build
  the identical ring from the same membership snapshot — no ring state
  ever crosses the wire, only membership.
* :class:`Directory` — the in-process membership authority: live
  shards, an explicit **generation** number bumped on every membership
  change, per-shard load reports for fleet-wide admission accounting,
  and the ``--max-jobs``/``--max-total-workers`` caps.
* :class:`DirectoryServer` — serves the directory over HTTP (stdlib
  ``ThreadingHTTPServer``; JSON bodies) plus the **hierarchical obs
  fold**: its ``/status`` and ``/metrics`` scrape every live shard's
  obs endpoint and merge them (``obs.export.merge_status_docs`` /
  ``merge_prometheus_pages``) — the same host-group merge idea the hier
  schedule uses, one level up.  A health-monitor thread probes shard
  ``/healthz``; a shard that misses its budget is removed, bumping the
  generation so the ring reassigns its jobs to survivors (which then
  journal-replay them — see ``shard.py``).
* :class:`DirectoryClient` — the cached client side.  Consumers hold a
  snapshot + locally-built ring and go back to the wire only on a
  miss, an explicit :meth:`DirectoryClient.invalidate` (driven by a
  ``REJECT_SHARD_MOVED`` redirect carrying a newer generation), or a
  refresh interval.

The directory process is deliberately SEPARATE from the shards it
indexes: killing a shard can never take the membership authority with
it.  Every shard additionally mirrors the latest snapshot on its own
obs endpoint (``GET /directory``) so clients can bootstrap from any
shard they already know.
"""
from __future__ import annotations

import argparse
import bisect
import hashlib
import json
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from rabit_tpu import chaos as chaos_mod
from rabit_tpu.obs import export as obs_export
from rabit_tpu.utils.checks import log

# Vnodes per shard on the ring.  64 keeps the moved-job fraction on a
# membership change near the ideal 1/N at single-digit shard counts
# while the full ring stays a few hundred points — rebuild is free.
DEFAULT_VNODES = 64
DEFAULT_PORT = 9400
DEFAULT_HEALTH_SEC = 1.0
DEFAULT_HEALTH_MISS = 5
_HTTP_TIMEOUT = 5.0


def _ring_hash(key: str) -> int:
    """64-bit ring point.  md5 rather than crc32: crc32 is linear, so
    names differing only in a trailing character land in correlated
    positions — a tenant fleet named job0..jobN can pile onto ONE
    shard.  md5's avalanche gives near-uniform arcs and spreads
    sequential names; cryptographic strength is irrelevant here, only
    determinism across processes (hashlib is seed-stable, unlike
    ``hash()``)."""
    return int.from_bytes(
        hashlib.md5(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over shard indices.

    Points are ``md5("shard<idx>:<vnode>")`` — deterministic from the
    (sorted) shard set alone, so two parties holding the same
    membership snapshot agree on every job's owner without exchanging
    the ring itself.  Adding or removing one shard moves only the jobs
    whose arc changed hands (~1/N of them), which is exactly what keeps
    a shard handoff a bounded replay instead of a fleet-wide reshuffle
    (pinned by tests/test_shard.py)."""

    def __init__(self, shards, vnodes: int = DEFAULT_VNODES) -> None:
        self._vnodes = max(int(vnodes), 1)
        points: list[tuple[int, int]] = []
        for idx in sorted({int(s) for s in shards}):
            for v in range(self._vnodes):
                points.append((_ring_hash(f"shard{idx}:{v}"), idx))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [i for _, i in points]

    def __len__(self) -> int:
        return len({i for i in self._owners})

    def owner(self, job: str) -> int:
        """Ring owner of ``job`` (first point clockwise of its hash).
        Raises :class:`LookupError` on an empty ring — the caller turns
        that into a retryable condition, not a default shard."""
        if not self._hashes:
            raise LookupError("hash ring is empty (no live shards)")
        pos = bisect.bisect_left(self._hashes, _ring_hash(str(job)))
        if pos == len(self._hashes):
            pos = 0
        return self._owners[pos]


class Directory:
    """In-process membership authority (one per fleet).

    Tracks live shards, their endpoints and last load report, the caps,
    and the **generation** — bumped on every membership change (shard
    registered at a new endpoint, shard removed) and NEVER on load
    reports, so cached rings stay valid exactly as long as membership
    does.  All methods are lock-guarded; :meth:`snapshot` is the only
    thing that crosses the wire."""

    def __init__(self, max_jobs: int = 0, max_total_workers: int = 0,
                 vnodes: int = DEFAULT_VNODES) -> None:
        self._lock = threading.RLock()
        self._shards: dict[int, dict] = {}
        self._generation = 0
        self._max_jobs = int(max_jobs)
        self._max_total_workers = int(max_total_workers)
        self._vnodes = int(vnodes)
        self._ring = HashRing([], self._vnodes)

    # -- membership ---------------------------------------------------
    def register(self, index: int, host: str, port: int,
                 obs_port: int = 0) -> dict:
        """Add (or re-register) a shard.  Idempotent for an unchanged
        endpoint — a shard's periodic re-register never churns the
        generation; a NEW index or a moved endpoint bumps it."""
        index = int(index)
        with self._lock:
            row = self._shards.get(index)
            endpoint = (str(host), int(port), int(obs_port))
            if row is None or (row["host"], row["port"],
                               row["obs_port"]) != endpoint:
                self._shards[index] = {
                    "host": str(host), "port": int(port),
                    "obs_port": int(obs_port),
                    "jobs": 0, "workers": 0, "ts": time.monotonic(),
                }
                self._generation += 1
                self._ring = HashRing(self._shards, self._vnodes)
                log("directory: shard %d @ %s:%d registered (gen %d)",
                    index, host, int(port), self._generation)
            else:
                row["ts"] = time.monotonic()
            return self._snapshot_locked()

    def remove(self, index: int) -> bool:
        """Drop a shard (health monitor or operator).  Bumps the
        generation so survivors adopt the dead shard's arc."""
        with self._lock:
            if int(index) not in self._shards:
                return False
            del self._shards[int(index)]
            self._generation += 1
            self._ring = HashRing(self._shards, self._vnodes)
            log("directory: shard %d removed (gen %d, %d left)",
                int(index), self._generation, len(self._shards))
            return True

    def poll(self, index: int, jobs: int = 0, workers: int = 0) -> dict:
        """A shard's periodic load report (doubles as its liveness
        beat).  Returns the snapshot so one round trip both reports and
        learns the current generation + fleet totals."""
        with self._lock:
            row = self._shards.get(int(index))
            if row is not None:
                row["jobs"] = max(int(jobs), 0)
                row["workers"] = max(int(workers), 0)
                row["ts"] = time.monotonic()
            return self._snapshot_locked()

    # -- queries ------------------------------------------------------
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def owner(self, job: str):
        """``(index, host, port)`` of the job's ring owner, or None on
        an empty fleet."""
        with self._lock:
            try:
                idx = self._ring.owner(job)
            except LookupError:
                return None
            row = self._shards[idx]
            return (idx, row["host"], row["port"])

    def snapshot(self) -> dict:
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:
        return {
            "generation": self._generation,
            "vnodes": self._vnodes,
            "shards": [{"index": i, "host": r["host"], "port": r["port"],
                        "obs_port": r["obs_port"], "jobs": r["jobs"],
                        "workers": r["workers"]}
                       for i, r in sorted(self._shards.items())],
            "caps": {"max_jobs": self._max_jobs,
                     "max_total_workers": self._max_total_workers},
            "fleet": {"jobs": sum(r["jobs"]
                                  for r in self._shards.values()),
                      "workers": sum(r["workers"]
                                     for r in self._shards.values())},
        }

    def stale(self, budget_sec: float) -> list[int]:
        """Shard indices whose last beat (register/poll) is older than
        ``budget_sec`` — candidates for the health monitor's probe."""
        now = time.monotonic()
        with self._lock:
            return [i for i, r in self._shards.items()
                    if now - r["ts"] > budget_sec]


def ring_from_snapshot(snap: dict) -> HashRing:
    """Rebuild the ring a snapshot implies — the shared client/shard
    path, so everyone hashes identically by construction."""
    return HashRing((s["index"] for s in snap.get("shards", ())),
                    int(snap.get("vnodes", DEFAULT_VNODES)))


def _http_json(url: str, payload: dict | None = None,
               timeout: float = _HTTP_TIMEOUT):
    """One JSON round trip (GET, or POST when ``payload`` given)."""
    data = None
    headers = {}
    if payload is not None:
        data = json.dumps(payload).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


class DirectoryClient:
    """Cached client over a :class:`DirectoryServer` (or any endpoint
    mirroring ``GET /directory`` — every shard does).

    Owner lookups hit the local ring; the wire is touched only on
    first use, after :meth:`invalidate` (a ``REJECT_SHARD_MOVED``
    redirect told us our generation is stale), or when ``max_age_sec``
    has passed — so the steady-state rendezvous path costs zero
    directory round trips."""

    def __init__(self, base_url: str, timeout: float = _HTTP_TIMEOUT,
                 max_age_sec: float = 30.0) -> None:
        self._base = str(base_url).rstrip("/")
        if "://" not in self._base:
            self._base = "http://" + self._base
        self._timeout = float(timeout)
        self._max_age = float(max_age_sec)
        self._lock = threading.Lock()
        self._snap: dict | None = None
        self._ring: HashRing | None = None
        self._fetched = 0.0

    @property
    def base_url(self) -> str:
        return self._base

    @property
    def generation(self) -> int:
        with self._lock:
            return int(self._snap["generation"]) if self._snap else -1

    def _adopt(self, snap: dict) -> dict:
        with self._lock:
            if (self._snap is None
                    or snap.get("generation", -1)
                    >= self._snap.get("generation", -1)):
                self._snap = snap
                self._ring = ring_from_snapshot(snap)
                self._fetched = time.monotonic()
            return self._snap

    def refresh(self) -> dict:
        """Fetch the authoritative snapshot now (raises ``OSError`` /
        ``urllib.error.URLError`` when the directory is unreachable —
        callers ride their existing retry budgets)."""
        return self._adopt(_http_json(self._base + "/directory",
                                      timeout=self._timeout))

    def invalidate(self, min_generation: int = -1) -> None:
        """Drop the cache if it is older than ``min_generation`` (from
        a redirect reason); the next lookup refreshes."""
        with self._lock:
            if (self._snap is None or min_generation < 0
                    or self._snap.get("generation", -1) < min_generation):
                self._snap = None
                self._ring = None

    def snapshot(self, refresh: bool = False) -> dict:
        with self._lock:
            snap, age = self._snap, time.monotonic() - self._fetched
        if snap is None or refresh or age > self._max_age:
            snap = self.refresh()
        return snap

    def owner(self, job: str):
        """``(index, host, port)`` of the job's owner per the cached
        ring (refreshing as needed), or None while the fleet is empty."""
        snap = self.snapshot()
        with self._lock:
            ring = self._ring
        if ring is None:
            return None
        try:
            idx = ring.owner(job)
        except LookupError:
            return None
        for s in snap.get("shards", ()):
            if s["index"] == idx:
                return (idx, s["host"], s["port"])
        return None

    def register(self, index: int, host: str, port: int,
                 obs_port: int = 0) -> dict:
        return self._adopt(_http_json(
            self._base + "/register",
            {"index": int(index), "host": host, "port": int(port),
             "obs_port": int(obs_port)}, timeout=self._timeout))

    def poll(self, index: int, jobs: int = 0, workers: int = 0) -> dict:
        return self._adopt(_http_json(
            self._base + "/poll",
            {"index": int(index), "jobs": int(jobs),
             "workers": int(workers)}, timeout=self._timeout))


class DirectoryServer:
    """HTTP face of a :class:`Directory` plus the thin global obs
    aggregator and the shard health monitor.

    Endpoints: ``GET /directory`` (snapshot), ``POST /register``,
    ``POST /poll`` (load report, returns snapshot), ``GET /healthz``,
    and the hierarchical fold — ``GET /status`` / ``GET /metrics``
    scrape every live shard's obs endpoint and merge, so ``rabit_top``
    pointed at the directory sees the whole fleet with per-job shard
    attribution.  Scrapes consult the chaos plan at the ``scrape`` site
    (reset/stall), and every injected fault surfaces as a counted
    failed scrape — the injected↔detected pairing the soak gate
    checks."""

    def __init__(self, directory: Directory, host: str = "127.0.0.1",
                 port: int = 0,
                 health_sec: float = DEFAULT_HEALTH_SEC,
                 health_miss: int = DEFAULT_HEALTH_MISS) -> None:
        self._dir = directory
        self._health_sec = float(health_sec)
        self._health_miss = max(int(health_miss), 1)
        self._miss: dict[int, int] = {}
        self._stop = threading.Event()
        self._counters = {"scrapes": 0, "scrape_failures": 0,
                          "chaos.injected": 0, "shards_removed": 0}
        self._clock = threading.Lock()
        self._chaos = chaos_mod.configure({}, identity="directory")
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet the stdlib
                pass

            def _reply(self, body: bytes, ctype: str,
                       code: int = 200) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, doc, code: int = 200) -> None:
                self._reply(json.dumps(doc, sort_keys=True).encode(),
                            "application/json", code)

            def do_GET(self):
                try:
                    path = self.path.split("?")[0]
                    if path == "/directory":
                        self._json(server._dir.snapshot())
                    elif path == "/status":
                        self._json(server.merged_status())
                    elif path == "/metrics":
                        self._reply(server.merged_metrics().encode(),
                                    "text/plain; version=0.0.4")
                    elif path in ("/", "/healthz"):
                        self._reply(b"ok\n", "text/plain")
                    else:
                        self.send_error(404)
                except Exception as e:  # noqa: BLE001 — serve thread
                    log("directory: GET %s failed: %s", self.path, e)
                    try:
                        self.send_error(500)
                    except OSError as e2:
                        log("directory: 500 reply failed: %s", e2)

            def do_POST(self):
                try:
                    path = self.path.split("?")[0]
                    n = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(n) or b"{}")
                    if path == "/register":
                        self._json(server._dir.register(
                            body["index"], body.get("host", "127.0.0.1"),
                            body["port"], body.get("obs_port", 0)))
                    elif path == "/poll":
                        self._json(server._dir.poll(
                            body["index"], body.get("jobs", 0),
                            body.get("workers", 0)))
                    else:
                        self.send_error(404)
                except Exception as e:  # noqa: BLE001 — serve thread
                    log("directory: POST %s failed: %s", self.path, e)
                    try:
                        self.send_error(500)
                    except OSError as e2:
                        log("directory: 500 reply failed: %s", e2)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._threads = [
            threading.Thread(target=self._httpd.serve_forever,
                             name="rabit-directory-http", daemon=True),
            threading.Thread(target=self._health_loop,
                             name="rabit-directory-health", daemon=True),
        ]

    def start(self) -> "DirectoryServer":
        for t in self._threads:
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    def _count(self, name: str, n: int = 1) -> None:
        with self._clock:
            self._counters[name] = self._counters.get(name, 0) + n

    # -- hierarchical obs fold ---------------------------------------
    def _scrape(self, url: str) -> str | None:
        """One shard obs-endpoint scrape, chaos-armed at the ``scrape``
        site.  Every failure (injected or organic) is counted, never
        raised — the fold degrades to the shards that answered."""
        self._count("scrapes")
        try:
            if self._chaos is not None:
                kind = self._chaos.link(chaos_mod.SITE_SCRAPE)
                if kind == chaos_mod.KIND_RESET:
                    self._count("chaos.injected")
                    raise ConnectionResetError("chaos: scrape reset")
            with urllib.request.urlopen(url, timeout=_HTTP_TIMEOUT) as r:
                return r.read().decode()
        except (OSError, urllib.error.URLError, ValueError) as e:
            self._count("scrape_failures")
            log("directory: scrape %s failed: %s", url, e)
            return None

    def _obs_targets(self) -> list[tuple[int, str]]:
        return [(s["index"], f"http://{s['host']}:{s['obs_port']}")
                for s in self._dir.snapshot()["shards"]
                if s.get("obs_port")]

    def merged_status(self) -> dict:
        docs = []
        for idx, base in self._obs_targets():
            text = self._scrape(base + "/status")
            if text is None:
                continue
            try:
                doc = json.loads(text)
            except ValueError:
                self._count("scrape_failures")
                continue
            if isinstance(doc, dict):
                doc.setdefault("shard", idx)
            docs.append(doc)
        out = obs_export.merge_status_docs(docs)
        out["directory"] = self._self_status()
        return out

    def merged_metrics(self) -> str:
        pages = []
        for _idx, base in self._obs_targets():
            text = self._scrape(base + "/metrics")
            if text is not None:
                pages.append(text)
        pages.append(self._self_metrics())
        return obs_export.merge_prometheus_pages(pages)

    def _self_status(self) -> dict:
        snap = self._dir.snapshot()
        with self._clock:
            counters = dict(self._counters)
        return {"generation": snap["generation"],
                "shards": [s["index"] for s in snap["shards"]],
                "fleet": snap["fleet"], "caps": snap["caps"],
                "counters": counters}

    def _self_metrics(self) -> str:
        snap = self._dir.snapshot()
        with self._clock:
            counters = dict(self._counters)
        samples = [("rabit_directory_generation", {},
                    snap["generation"]),
                   ("rabit_directory_shards", {}, len(snap["shards"])),
                   ("rabit_directory_fleet_jobs", {},
                    snap["fleet"]["jobs"]),
                   ("rabit_directory_fleet_workers", {},
                    snap["fleet"]["workers"])]
        types = {"rabit_directory_generation": "counter"}
        for name, v in sorted(counters.items()):
            series = "rabit_directory_" + name.replace(".", "_")
            samples.append((series, {}, v))
            types[series] = "counter"
        return obs_export.prometheus_text(samples, types)

    # -- health monitor ----------------------------------------------
    def _health_loop(self) -> None:
        """Probe each shard's ``/healthz`` every ``health_sec``; after
        ``health_miss`` consecutive misses the shard is removed — the
        generation bump that starts the handoff choreography."""
        while not self._stop.wait(self._health_sec):
            for s in self._dir.snapshot()["shards"]:
                idx = s["index"]
                if not s.get("obs_port"):
                    continue  # not probeable; rely on poll staleness
                url = f"http://{s['host']}:{s['obs_port']}/healthz"
                try:
                    with urllib.request.urlopen(url, timeout=2.0) as r:
                        r.read()
                    self._miss[idx] = 0
                except (OSError, urllib.error.URLError):
                    self._miss[idx] = self._miss.get(idx, 0) + 1
                    if self._miss[idx] >= self._health_miss:
                        if self._dir.remove(idx):
                            self._count("shards_removed")
                        self._miss.pop(idx, None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rabit_tpu.tracker.directory",
        description="Job directory / global obs aggregator for the "
                    "sharded tracker control plane.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT,
                    help="directory port (0 = ephemeral)")
    ap.add_argument("--max-jobs", type=int, default=0,
                    help="fleet-wide concurrent-job cap (0 = unlimited)")
    ap.add_argument("--max-total-workers", type=int, default=0,
                    help="fleet-wide worker-sum cap (0 = unlimited)")
    ap.add_argument("--vnodes", type=int, default=DEFAULT_VNODES)
    ap.add_argument("--health-sec", type=float,
                    default=DEFAULT_HEALTH_SEC)
    ap.add_argument("--health-miss", type=int,
                    default=DEFAULT_HEALTH_MISS)
    args = ap.parse_args(argv)
    directory = Directory(max_jobs=args.max_jobs,
                          max_total_workers=args.max_total_workers,
                          vnodes=args.vnodes)
    server = DirectoryServer(directory, host=args.host, port=args.port,
                             health_sec=args.health_sec,
                             health_miss=args.health_miss).start()
    sys.stderr.write(
        f"directory listening on {server.host}:{server.port}\n")
    sys.stderr.flush()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
