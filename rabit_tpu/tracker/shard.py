"""One tracker shard of the partitioned control plane.

:class:`ShardServer` is a :class:`~rabit_tpu.tracker.tracker.Tracker`
that hosts only the jobs the directory's consistent-hash ring assigns
to it (doc/fault_tolerance.md "Sharded tracker").  Everything below the
admission seam — rendezvous, heartbeats, elastic epochs, journaling,
obs folding — is the battle-tested single-tracker machinery, unchanged;
the shard adds exactly three behaviours:

* **Ownership-checked admission.**  A registration for a job whose
  ring owner is another shard gets the typed ``REJECT_SHARD_MOVED``
  reply whose reason carries ``gen/shard/endpoint`` so the worker
  re-targets without a directory round trip.  A job already live here
  stays here until it finishes (sticky), so a mid-life membership
  change never strands a running job.
* **Journaled handoff.**  All shards share one ``--state-dir`` root.
  The generation-poll thread watches the directory; when a membership
  change hands this shard an arc whose previous owner is GONE from the
  fleet (the failover case), it replays the dead shard's job journals
  through the existing HA restore path.  While the replay runs, every
  racing submission gets the typed ``REJECT_REPLAYING`` backoff reject
  (linger-covered) — never a silent close, never a duplicate
  ``JobState`` on two shards.
* **Fleet-wide admission accounting.**  The caps live on the
  directory; each shard admits against the fleet totals from its last
  poll plus its own exact local counts, so rejects stay typed,
  stateless and deterministic given the polled snapshot.
* **Live job migration** (ISSUE 19, opt-in via ``migrate_after_sec``).
  A RUNNING job whose ring owner moved away (scale-up, sustained
  imbalance) is handed to its ring-correct owner at a commit boundary:
  the source arms a same-world pending rescale (the commit-boundary
  re-registration signal the epoch-poll choreography already carries),
  flushes the journal, and OFFERS the job to the destination over
  ``POST /migrate`` on its obs endpoint.  The destination — fenced by
  generation and by ITS OWN ring — replays the journal through the
  replay gate and answers ok; only then does the source detach the
  journal store, drop the job, and leave a **tombstone**: every later
  registration gets ``REJECT_SHARD_MOVED`` naming the destination,
  epoch polls get a forced epoch bump so workers re-register at their
  next commit boundary, and a goodbye that races the discovery window
  is FORWARDED (``POST /goodbye``) so a finishing job's books never
  lose the terminal count.  A refused offer rolls back completely —
  the job stays sticky here.  The same bounded pass (``migrate_max``
  per poll tick) is the cold-restart drain: a whole-fleet restart
  adopts by the CURRENT ring at bootstrap, and any straggler the
  settling membership re-maps afterwards is drained by migration.

A plain ``Tracker`` (no directory) remains the exact legacy
single-shard control plane — the wire is byte-identical both
directions, pinned by tests/test_shard.py.
"""
from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

from rabit_tpu import chaos as chaos_mod
from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.directory import (DEFAULT_VNODES, DirectoryClient,
                                         HashRing, ring_from_snapshot)
from rabit_tpu.tracker.tracker import JobState, Tracker, _AdmissionReject
from rabit_tpu.utils.checks import log

DEFAULT_POLL_SEC = 0.5
DEFAULT_MIGRATE_MAX = 2
# Bounded tombstone memory: a redirect target may be needed for as
# long as a slow worker keeps dialing the old owner, but an unbounded
# dict on a long-lived shard is a leak.  FIFO eviction; an evicted
# name degrades to the ordinary ownership reject (one extra directory
# consult on the worker).
_TOMBSTONE_CAP = 256
_MIGRATE_HTTP_TIMEOUT = 5.0
# Directory registration at construction: bounded, backed-off retries.
# The directory may be mid-failover (leader lease flipping) or a chaos
# rule may reset the link — both are transient by contract.
_REGISTER_TRIES = 6


class ShardServer(Tracker):
    """One shard among peers behind a job directory.

    ``directory`` is either a base URL (subprocess deployments — a
    :class:`DirectoryClient` is built over it) or an in-process
    :class:`Directory` authority (tests, ``rendezvous_storm --shards``).
    The shard registers itself at construction, adopts any journals it
    already owns, then keeps a poll thread reporting load and watching
    the generation."""

    def __init__(self, n_workers: int, host: str = "127.0.0.1",
                 port: int = 0, *, shard_index: int,
                 directory, poll_sec: float = DEFAULT_POLL_SEC,
                 state_dir: str | None = None,
                 migrate_after_sec: float | None = None,
                 migrate_max: int = DEFAULT_MIGRATE_MAX, **kw) -> None:
        self._shard_index = int(shard_index)
        self._dir = (DirectoryClient(directory)
                     if isinstance(directory, str) else directory)
        self._poll_sec = max(float(poll_sec), 0.05)
        self._shard_lock = threading.Lock()
        self._snap: dict | None = None
        self._ring = None
        self._gen = -1
        self._prev_members: frozenset[int] = frozenset()
        self._last_reported = (0, 0)
        # Live migration is OPT-IN: with the threshold unset a live job
        # stays sticky on its shard until it finishes (the PR-16
        # contract, pinned by test_sticky_job_survives_membership_
        # growth).  With it set, a job misowned for longer than the
        # threshold is drained to its ring owner, migrate_max per tick.
        self._migrate_after = (float(migrate_after_sec)
                               if migrate_after_sec is not None else None)
        self._migrate_max = max(int(migrate_max), 1)
        self._misowned_since: dict[str, float] = {}
        # Migrated-away jobs: name -> redirect coordinates.  Consulted
        # by _admit (typed reject), epoch polls (forced epoch bump) and
        # goodbye forwarding.  Bounded FIFO (_TOMBSTONE_CAP).
        self._tombstones: dict[str, dict] = {}
        # One log line per directory-outage episode, not per poll tick
        # (ISSUE 19 satellite): failures are always COUNTED, the text
        # log only marks the episode's edges.
        self._dir_down = False
        # Armed while adopted journals replay: _admit turns every
        # racing submission into the typed REJECT_REPLAYING.
        self._replay_gate = threading.Event()
        self._poll_stop = threading.Event()
        # The base restore path replays EVERY journal under state_dir —
        # correct for a lone tracker, wrong for one shard of a shared
        # root.  Construct without it, then adopt ownership-filtered.
        super().__init__(n_workers, host, port, state_dir=None, **kw)
        self._state_base = str(state_dir) if state_dir else None
        if isinstance(self._dir, DirectoryClient):
            plan = chaos_mod.configure(
                {}, identity=f"shard{self._shard_index}")
            if plan is not None:
                self._dir.attach_chaos(plan)
        snap = self._register_with_retry()
        self._adopt_snapshot(snap)
        self._adopt_owned_jobs(bootstrap=True)
        threading.Thread(target=self._poll_loop,
                         name=f"rabit-shard{self._shard_index}-poll",
                         daemon=True).start()

    def _register_with_retry(self) -> dict:
        """Register with the directory, riding transient failures
        (replica failover window, injected dir_register resets) on a
        bounded backed-off retry.  Every retry is counted — the
        detection half of the ``dir_register`` chaos pairing gate."""
        last: Exception | None = None
        for attempt in range(_REGISTER_TRIES):
            try:
                return self._dir.register(self._shard_index, self.host,
                                          self.port, self.obs_port or 0)
            except (OSError, urllib.error.URLError, ValueError) as e:
                last = e
                self._count("shard.register_retries")
                log("shard %d: directory registration attempt %d "
                    "failed: %s", self._shard_index, attempt + 1, e)
                time.sleep(min(0.05 * (2 ** attempt), 1.0))
        raise OSError(
            f"shard {self._shard_index}: directory registration failed "
            f"after {_REGISTER_TRIES} attempts: {last}")

    # -- directory membership ------------------------------------------
    def _adopt_snapshot(self, snap: dict) -> bool:
        """Install a directory snapshot; True when the generation moved
        forward (membership changed — the ring must be rebuilt and an
        adoption pass considered)."""
        if not isinstance(snap, dict):
            return False
        gen = int(snap.get("generation", -1))
        with self._shard_lock:
            if gen < self._gen:
                return False
            if gen == self._gen:
                self._snap = snap  # fresher fleet counts, same ring
                return False
            self._prev_members = frozenset(
                s["index"] for s in (self._snap or {}).get("shards", ()))
            self._snap = snap
            self._gen = gen
            self._ring = ring_from_snapshot(snap)
            members = [s["index"] for s in snap.get("shards", ())]
        self._count("shard.generation")
        log("shard %d: directory generation %d (shards %s)",
            self._shard_index, gen, members)
        return True

    def _poll_loop(self) -> None:
        """Report load / learn the generation every ``poll_sec``.  The
        poll doubles as this shard's liveness beat; a directory outage
        is ridden out on the cached snapshot (admission keeps its last
        known ring — bounded staleness, never a stall)."""
        while not self._poll_stop.wait(self._poll_sec):
            with self._jobs_lock:
                active = [j for j in self._jobs.values()
                          if j.touched and not j.done]
                jobs = len(active)
                workers = sum(j.n_workers for j in active)
            try:
                snap = self._dir.poll(self._shard_index, jobs=jobs,
                                      workers=workers)
                self._last_reported = (jobs, workers)
                if self._shard_index not in {
                        s["index"] for s in snap.get("shards", ())}:
                    # Health-removed while alive (an obs hiccup), or a
                    # restarted directory: re-assert our membership.
                    snap = self._dir.register(
                        self._shard_index, self.host, self.port,
                        self.obs_port or 0)
            except (OSError, urllib.error.URLError, ValueError) as e:
                # Always counted; logged once per outage EPISODE — a
                # poll-tick cadence must never become a warning-per-
                # tick firehose during a long directory outage.
                self._count("shard.poll_failures")
                if not self._dir_down:
                    self._dir_down = True
                    self._count("shard.dir_outages")
                    log("shard %d: directory poll failed (%s); riding "
                        "the cached snapshot, further failures counted "
                        "silently until recovery", self._shard_index, e)
                continue
            if self._dir_down:
                self._dir_down = False
                log("shard %d: directory poll recovered",
                    self._shard_index)
            if self._adopt_snapshot(snap):
                self._adopt_owned_jobs()
            self._maybe_migrate()

    def stop(self) -> None:
        self._poll_stop.set()
        super().stop()

    # -- journaled handoff ---------------------------------------------
    def _owner(self, name: str) -> int | None:
        with self._shard_lock:
            ring = self._ring
        if ring is None:
            return None
        try:
            return ring.owner(name)
        except LookupError:
            return None

    def _restore_named_jobs(self) -> None:
        """Disabled for shards (state_dir is withheld from the base
        constructor anyway): all replay goes through the ownership-
        filtered :meth:`_adopt_owned_jobs`."""

    def _journal_names(self) -> list[str]:
        try:
            names = sorted(os.listdir(self._state_base))
        except OSError:
            return []
        return [n for n in names
                if n != P.DEFAULT_JOB and P.valid_job_id(n)
                and os.path.isdir(os.path.join(self._state_base, n))]

    def _live_elsewhere(self, name: str) -> bool:
        """Is the job being served RIGHT NOW by the shard that owned
        it before this one joined?  A membership GROWTH leaves a job
        live on its sticky previous owner — bootstrap must not
        re-replay it (that is the duplicate-JobState bug, and it
        double-enters the fleet books); the live-migration drain moves
        it here at a commit boundary instead, with the books
        transferred rather than re-entered.  A whole-fleet cold
        restart has no live previous owner, so everything owned is
        adopted.  An unreachable previous owner reads as restarting —
        adopt; generation fencing bounds a mistaken double-admit."""
        with self._shard_lock:
            snap = self._snap
        rows = {s["index"]: s for s in (snap or {}).get("shards", ())}
        others = sorted(i for i in rows if i != self._shard_index)
        if not others:
            return False
        try:
            prev = HashRing(others, int((snap or {}).get(
                "vnodes", DEFAULT_VNODES))).owner(name)
        except LookupError:
            return False
        row = rows.get(prev)
        if row is None or not row.get("obs_port"):
            return False
        try:
            with urllib.request.urlopen(
                    f"http://{row['host']}:{row['obs_port']}/status",
                    timeout=2.0) as resp:
                doc = json.loads(resp.read().decode())
        except (OSError, urllib.error.URLError, ValueError):
            return False
        return name in (doc.get("jobs") or {})

    def _adopt_owned_jobs(self, bootstrap: bool = False) -> None:
        """Replay journals for arcs this shard now owns.

        A journal is adopted when the current ring maps its job here
        AND its previous owner left the fleet (that shard's death is
        what moved the arc) — a membership GROWTH never re-replays a
        job that is still live on its sticky previous owner, which
        would be the duplicate-JobState bug.  ``bootstrap`` (first pass
        after registration, journals present = whole-fleet cold
        restart) adopts everything owned regardless of history.  The
        replay gate is armed for the whole pass: racing submissions
        get REJECT_REPLAYING, then retry into a consistent shard."""
        if not self._state_base:
            return
        with self._shard_lock:
            gen = self._gen
            prev = self._prev_members
            members = frozenset(
                s["index"] for s in (self._snap or {}).get("shards", ()))
        removed = prev - members
        if not bootstrap and not removed:
            return
        self._replay_gate.set()
        try:
            adopted = 0
            for name in self._journal_names():
                if self._owner(name) != self._shard_index:
                    continue
                with self._jobs_lock:
                    live = self._jobs.get(name)
                    if live is not None and not live.done:
                        continue  # already hosted here
                if bootstrap and self._live_elsewhere(name):
                    continue  # scale-up join: the sticky owner still
                    # serves it — the migration drain moves it here
                job = self._replay_job(name)
                if job is not None:
                    with self._jobs_lock:
                        self._jobs[name] = job
                    self._mark_restored(job)
                    adopted += 1
            # The default job journals at the state root; its arc moves
            # like any named job's.
            if self._owner(P.DEFAULT_JOB) == self._shard_index \
                    and not (bootstrap
                             and self._live_elsewhere(P.DEFAULT_JOB)):
                default = self._default_job()
                if not default.touched and default._state_store is None:
                    try:
                        default.attach_store(ckpt_mod.CheckpointStore(
                            self._state_base, rank=0, keep=3))
                        if default.restore_journal() and not default.done:
                            self._mark_restored(default)
                            adopted += 1
                    except OSError as e:
                        log("shard %d: default job journal "
                            "unavailable: %s", self._shard_index, e)
            if adopted:
                self._count("shard.jobs_adopted", adopted)
                log("shard %d: adopted %d job journal(s) at "
                    "generation %d", self._shard_index, adopted, gen)
        finally:
            self._replay_gate.clear()

    def _replay_job(self, name: str) -> JobState | None:
        """Replay one named job's journal from the shared state root
        into a fresh (not yet installed) :class:`JobState`, or None
        when there is nothing live to replay.  Shared by dead-shard
        adoption and the live-migration accept path."""
        if not self._state_base:
            return None
        job = JobState(self, name, self._default_world)
        if self._obs_base:
            job._obs_dir = os.path.join(self._obs_base, name)
        sub = os.path.join(self._state_base, name)
        try:
            job.attach_store(ckpt_mod.CheckpointStore(
                sub, rank=0, keep=3))
        except OSError as e:
            log("shard %d: cannot open job %r journal: %s",
                self._shard_index, name, e)
            return None
        if job.restore_journal() and not job.done:
            return job
        return None

    # -- live migration (ISSUE 19) --------------------------------------
    def _migratable(self, job: JobState) -> bool:
        """Commit-boundary quiescence: only a job with settled
        membership and an attached journal can be shipped.  A pending
        rescale, parked registrants, or members that already said
        goodbye all mean the job is mid-transition — it stays sticky
        until a later tick finds it quiet."""
        if (not job.touched or job.done or not job._members
                or job.name == P.DEFAULT_JOB
                or job._state_store is None or job._shutdown_tasks):
            return False
        with job._scale_lock:
            if job._target_world is not None:
                return False
        with job._pending_lock:
            if job._pending:
                return False
        return True

    def _maybe_migrate(self) -> None:
        """One bounded drain-and-move pass (poll-tick cadence): jobs
        whose ring owner has been another shard for longer than
        ``migrate_after_sec`` are offered to it, at most
        ``migrate_max`` per tick.  This is both the scale-up/imbalance
        drain and the cold-restart straggler drain — bootstrap
        adoption placed everything by the then-current ring; anything
        the settling membership re-mapped flows through here."""
        if self._migrate_after is None or self._replay_gate.is_set():
            return
        now = time.monotonic()
        moved = 0
        for job in self._active_jobs():
            owner = self._owner(job.name)
            if owner is None or owner == self._shard_index \
                    or job.name == P.DEFAULT_JOB:
                self._misowned_since.pop(job.name, None)
                continue
            since = self._misowned_since.setdefault(job.name, now)
            if now - since < self._migrate_after:
                continue
            if moved >= self._migrate_max:
                break  # bounded pass; next tick continues the drain
            if self._migrate_job(job, owner):
                self._misowned_since.pop(job.name, None)
                moved += 1
        live = {j.name for j in self._active_jobs()}
        for name in [n for n in self._misowned_since if n not in live]:
            self._misowned_since.pop(name, None)

    def _migrate_job(self, job: JobState, owner: int) -> bool:
        """Hand one RUNNING job to its ring owner.  The choreography
        (doc/fault_tolerance.md "Replicated directory & job
        migration"):

        1. quiescence check, then arm a SAME-WORLD pending rescale —
           the signal the epoch-poll choreography already turns into a
           commit-boundary re-registration on every worker;
        2. flush the journal (the state the destination will replay);
        3. offer over ``POST /migrate`` — the destination fences by
           generation and by its own ring, replays through its replay
           gate, and only then answers ok;
        4. on accept: detach the journal store UNDER the journal lock
           (a racing write after this point must become a no-op, not a
           torn file the destination half-replayed), drop the job,
           tombstone the name, close its sockets;
        5. on refusal: roll the pending rescale back — the job stays
           sticky, nothing observable happened.
        """
        with self._shard_lock:
            gen, snap = self._gen, self._snap
        dest = next((s for s in (snap or {}).get("shards", ())
                     if s["index"] == owner), None)
        if dest is None or not dest.get("obs_port"):
            return False  # owner not probeable; retry next tick
        if not self._migratable(job):
            return False
        with job._scale_lock:
            job._target_world = job.n_workers
        job._journal()
        url = (f"http://{dest['host']}:{dest['obs_port']}/migrate")
        payload = {"job": job.name, "generation": gen,
                   "src": self._shard_index, "world": job.n_workers,
                   "epoch": job._epoch}
        doc = None
        try:
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=_MIGRATE_HTTP_TIMEOUT) as resp:
                doc = json.loads(resp.read().decode())
        except (OSError, urllib.error.URLError, ValueError) as e:
            log("shard %d: migration offer of job %r to shard %d "
                "failed: %s", self._shard_index, job.name, owner, e)
        if not (isinstance(doc, dict) and doc.get("ok")):
            with job._scale_lock:
                job._target_world = None
            job._journal()
            self._count("job.migrate_refused")
            if isinstance(doc, dict):
                log("shard %d: shard %d refused job %r: %s",
                    self._shard_index, owner, job.name,
                    doc.get("reason", "?"))
            return False
        # Accepted: the destination now owns the journal.  Silence our
        # writer FIRST (under the journal lock — a mid-write race must
        # finish or never start before the store detaches), then drop.
        with job._journal_lock:
            job._state_store = None
        with self._jobs_lock:
            self._jobs.pop(job.name, None)
        if len(self._tombstones) >= _TOMBSTONE_CAP:
            self._tombstones.pop(next(iter(self._tombstones)))
        self._tombstones[job.name] = {
            "gen": max(gen, int(doc.get("generation", gen))),
            "shard": owner, "host": dest["host"], "port": dest["port"],
            "epoch": job._epoch, "world": job.n_workers}
        job.close()
        self._count("job.migrated_out")
        log("shard %d: job %r migrated to shard %d (generation %d, "
            "epoch %d, world %d)", self._shard_index, job.name, owner,
            self._tombstones[job.name]["gen"], job._epoch,
            job.n_workers)
        return True

    def _accept_migration(self, body: dict) -> dict:
        """``POST /migrate``: the destination half of the handoff.
        Every refusal is typed and leaves no state — the source rolls
        back and the job stays where it was.  The fence: this shard
        admits the job only if ITS ring (refreshed to at least the
        offered generation) maps the name here — a racing submitter on
        a third shard sees REJECT_REPLAYING during the replay and the
        ordinary ownership redirect after it, never a second
        admission."""
        name = str(body.get("job", ""))
        offered_gen = int(body.get("generation", -1))
        if not P.valid_job_id(name) or name == P.DEFAULT_JOB:
            return {"ok": False, "reason": "bad_job"}
        if not self._state_base:
            return {"ok": False, "reason": "no_state_dir"}
        if self._replay_gate.is_set():
            return {"ok": False, "reason": "replaying"}
        with self._shard_lock:
            gen = self._gen
        if gen < offered_gen:
            # The offer was decided on a newer ring than ours: catch
            # up before judging ownership.
            try:
                if isinstance(self._dir, DirectoryClient):
                    self._adopt_snapshot(self._dir.snapshot(refresh=True))
                else:
                    self._adopt_snapshot(self._dir.snapshot())
                with self._shard_lock:
                    gen = self._gen
            except (OSError, urllib.error.URLError, ValueError):
                self._count("shard.refresh_failures")
        if gen < offered_gen:
            return {"ok": False, "reason": "stale_gen", "generation": gen}
        if self._owner(name) != self._shard_index:
            return {"ok": False, "reason": "not_owner", "generation": gen}
        with self._jobs_lock:
            live = self._jobs.get(name)
            if live is not None and not live.done:
                # Idempotent accept: a lost reply's retry must not
                # re-replay a job this shard already runs.
                return {"ok": True, "generation": gen, "dup": True}
        self._replay_gate.set()
        try:
            job = self._replay_job(name)
            if job is None:
                return {"ok": False, "reason": "no_journal",
                        "generation": gen}
            # Guarantee the commit-boundary choreography lands: the
            # re-registering world must complete as a RESCALE round
            # (epoch bump to what the source's tombstone promises),
            # even if a racing recompute cleared the shipped target.
            with job._scale_lock:
                if job._target_world is None:
                    job._target_world = job.n_workers
            with self._jobs_lock:
                self._jobs[name] = job
            # Lifecycle, NOT _mark_restored: the source shard is alive
            # and its job.created count stands, so counting a restore
            # here would double-enter the fleet books
            # (created+restored == finished+orphan_gc).
            if not job.touched:
                job.touched = True
                self._jobs_touched += 1
            self._count("job.migrated_in")
            job._journal()
            self._tombstones.pop(name, None)
            log("shard %d: job %r migrated in from shard %s "
                "(generation %d, epoch %d, world %d)",
                self._shard_index, name, body.get("src", "?"), gen,
                job._epoch, job.n_workers)
            return {"ok": True, "generation": gen}
        finally:
            self._replay_gate.clear()

    def _forward_goodbye(self, name: str, task_id: str) -> None:
        """A goodbye for a migrated-away job raced the workers'
        discovery window: forward it to the destination (one bounded
        best-effort POST) so the terminal count lands where the job now
        lives — otherwise a job finishing entirely inside the window
        would leak as an eventual orphan GC and unbalance the books."""
        tomb = self._tombstones.get(name)
        if tomb is None:
            return
        dest = None
        with self._shard_lock:
            snap = self._snap
        for s in (snap or {}).get("shards", ()):
            if s["index"] == tomb["shard"] and s.get("obs_port"):
                dest = (s["host"], s["obs_port"])
        if dest is None:
            self._count("shard.goodbye_forward_failures")
            return
        try:
            req = urllib.request.Request(
                f"http://{dest[0]}:{dest[1]}/goodbye",
                data=json.dumps({"job": name, "task": task_id}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(
                    req, timeout=_MIGRATE_HTTP_TIMEOUT) as resp:
                resp.read()
            self._count("shard.goodbyes_forwarded")
        except (OSError, urllib.error.URLError, ValueError) as e:
            self._count("shard.goodbye_forward_failures")
            log("shard %d: goodbye forward for job %r task %r "
                "failed: %s", self._shard_index, name, task_id, e)

    def _handle_http_post(self, path: str, body: dict) -> dict | None:
        if path == "/migrate":
            return self._accept_migration(body)
        if path == "/goodbye":
            name = str(body.get("job", ""))
            task_id = str(body.get("task", ""))
            job = self._job_get(name)
            if job is None:
                return {"ok": False, "reason": "unknown_job"}
            job.last_activity = time.monotonic()
            if task_id in job._rank_of:
                job._shutdown_tasks.add(task_id)
            if job.job_done():
                self._finish_job(job, "finished")
            else:
                job._journal()
            return {"ok": True}
        return super()._handle_http_post(path, body)

    def _dispatch(self, sock, job_name: str, cmd: str, task_id: str,
                  world_hint: int) -> None:
        """Tombstone interception in front of the base dispatch: a
        worker still talking to the OLD owner of a migrated job gets
        steered, not dropped.  Epoch polls see a forced epoch bump
        (their commit boundary then re-registers, which the admission
        override redirects); goodbyes are forwarded so the books close
        at the destination; registrations fall through to _admit's
        typed redirect.  Heartbeats fall through to the base job=None
        close — the engine counts the drop and re-resolves."""
        tomb = self._tombstones.get(job_name)
        if tomb is not None and self._job_get(job_name) is None:
            if cmd == P.CMD_EPOCH:
                try:
                    P.recv_u32(sock)  # committed version; job is gone
                    P.send_u32(sock, int(tomb["epoch"]))
                    P.send_u32(sock, int(tomb["epoch"]) + 1)
                    P.send_u32(sock, int(tomb["world"]))
                except OSError:
                    pass
                self._count("shard.tombstone_epoch_bumps")
                sock.close()
                return
            if cmd == P.CMD_SHUTDOWN:
                self._forward_goodbye(job_name, task_id)
                sock.close()
                return
        super()._dispatch(sock, job_name, cmd, task_id, world_hint)

    # -- admission ------------------------------------------------------
    def _admit(self, name: str, world_hint: int) -> JobState:
        """Ownership + fleet capacity in front of the base admission.
        Every reject below raises BEFORE any job state exists — the
        same stateless contract as the base checks."""
        with self._shard_lock:
            gen, snap = self._gen, self._snap
        if self._replay_gate.is_set():
            raise _AdmissionReject(
                P.REJECT_REPLAYING, "replaying",
                f"job {name!r} refused: shard {self._shard_index} is "
                f"replaying adopted journals (generation {gen}); "
                "back off and retry")
        tomb = self._tombstones.get(name)
        if tomb is not None:
            if self._owner(name) == self._shard_index:
                # The ring moved the name BACK here since the
                # migration (another membership change): the tombstone
                # would bounce workers to a shard that will bounce
                # them straight back — drop it and let the ordinary
                # ownership/adoption path decide.
                self._tombstones.pop(name, None)
            else:
                self._count("shard.tombstone_redirects")
                raise _AdmissionReject(
                    P.REJECT_SHARD_MOVED, "shard_moved",
                    P.shard_moved_reason(int(tomb["gen"]),
                                         int(tomb["shard"]),
                                         tomb["host"], int(tomb["port"])))
        with self._jobs_lock:
            live = self._jobs.get(name)
            sticky = live is not None and not live.done
        if not sticky:
            # Admitting a NEW job on a stale ring is the duplicate-
            # JobState bug (two shards each believing they own it), so
            # new-job admission re-reads the authoritative snapshot —
            # one round trip, paid only on the rare job-creation path.
            # A directory outage falls back to the cached ring
            # (bounded staleness beats refusing all work).
            try:
                fresh = (self._dir.snapshot(refresh=True)
                         if isinstance(self._dir, DirectoryClient)
                         else self._dir.snapshot())
                if self._adopt_snapshot(fresh):
                    # The refresh revealed a membership change: adopt
                    # any newly-owned journals BEFORE admitting, or a
                    # handed-off job would be re-created fresh (its
                    # journal orphaned) inside the poll-tick window.
                    self._adopt_owned_jobs()
                with self._shard_lock:
                    gen, snap = self._gen, self._snap
            except (OSError, urllib.error.URLError, ValueError) as e:
                self._count("shard.refresh_failures")
                log("shard %d: admission-time directory refresh "
                    "failed (%s); using the cached ring",
                    self._shard_index, e)
            owner = self._owner(name)
            if owner is not None and owner != self._shard_index:
                endpoint = ("", 0)
                for s in (snap or {}).get("shards", ()):
                    if s["index"] == owner:
                        endpoint = (s["host"], s["port"])
                raise _AdmissionReject(
                    P.REJECT_SHARD_MOVED, "shard_moved",
                    P.shard_moved_reason(gen, owner, endpoint[0],
                                         endpoint[1]))
            self._check_fleet_capacity(name, world_hint, snap)
        return super()._admit(name, world_hint)

    def _check_fleet_capacity(self, name: str, world_hint: int,
                              snap: dict | None) -> None:
        """Fleet-wide ``--max-jobs``/``--max-total-workers`` (held by
        the directory).  Remote load is the fleet total from the last
        poll minus what this shard itself reported then; local load is
        exact.  Bounded staleness (one poll period), deterministic
        given the snapshot."""
        caps = (snap or {}).get("caps") or {}
        max_jobs = int(caps.get("max_jobs") or 0)
        max_workers = int(caps.get("max_total_workers") or 0)
        if not max_jobs and not max_workers:
            return
        fleet = (snap or {}).get("fleet") or {}
        rep_jobs, rep_workers = self._last_reported
        with self._jobs_lock:
            active = [j for j in self._jobs.values()
                      if j.touched and not j.done]
            local_jobs = len(active)
            local_workers = sum(j.n_workers for j in active)
        remote_jobs = max(int(fleet.get("jobs", 0)) - rep_jobs, 0)
        remote_workers = max(int(fleet.get("workers", 0)) - rep_workers,
                             0)
        world = (world_hint if world_hint > 0 and name != P.DEFAULT_JOB
                 else self._default_world)
        if max_jobs and remote_jobs + local_jobs >= max_jobs:
            raise _AdmissionReject(
                P.REJECT_MAX_JOBS, "jobs",
                f"job {name!r} refused: {remote_jobs + local_jobs} "
                f"active job(s) fleet-wide at the --max-jobs="
                f"{max_jobs} capacity; retry after one finishes")
        if max_workers and (remote_workers + local_workers + world
                            > max_workers):
            raise _AdmissionReject(
                P.REJECT_MAX_WORKERS, "workers",
                f"job {name!r} refused: {remote_workers + local_workers}"
                f" worker(s) active fleet-wide + {world} requested "
                f"exceeds --max-total-workers={max_workers}; retry "
                "after one finishes")

    def _service_done(self) -> bool:
        """A shard never self-retires.  The base tracker exits once
        every admitted job finished; a shard is one member of a
        long-lived fleet — the next submission may hash onto it at any
        moment, and its /status must stay scrapeable for the
        hierarchical fold after its last job closes.  Operator stop
        (:meth:`stop` / SIGTERM) ends it."""
        return False

    # -- obs ------------------------------------------------------------
    def _render_status(self) -> dict:
        out = super()._render_status()
        out["shard"] = self._shard_index
        with self._shard_lock:
            out["directory"] = {"generation": self._gen,
                                "shards": sorted(
                                    s["index"] for s in
                                    (self._snap or {}).get("shards", ()))}
        if isinstance(self._dir, DirectoryClient):
            out["directory"]["stale_rides"] = self._dir.stale_rides
            out["directory"]["stale_warnings"] = self._dir.stale_warnings
        if self._tombstones:
            out["tombstones"] = {
                name: {"shard": t["shard"], "gen": t["gen"]}
                for name, t in self._tombstones.items()}
        for row in out["jobs"].values():
            row.setdefault("shard", self._shard_index)
        return out

    def _render_http_extra(self, path: str) -> tuple[str, str] | None:
        """Mirror the latest directory snapshot on this shard's obs
        endpoint (``GET /directory``) — the directory is "served by
        every shard", so a client can bootstrap from any one of them."""
        if path == "/directory":
            import json
            with self._shard_lock:
                snap = self._snap
            if snap is None:
                return None
            return (json.dumps(snap, sort_keys=True),
                    "application/json")
        return super()._render_http_extra(path)

    def worker_env(self, task_id: str,
                   job: str | None = None) -> dict[str, str]:
        env = super().worker_env(task_id, job)
        if isinstance(self._dir, DirectoryClient):
            env["RABIT_DIRECTORY"] = self._dir.base_url
        return env
