"""One tracker shard of the partitioned control plane.

:class:`ShardServer` is a :class:`~rabit_tpu.tracker.tracker.Tracker`
that hosts only the jobs the directory's consistent-hash ring assigns
to it (doc/fault_tolerance.md "Sharded tracker").  Everything below the
admission seam — rendezvous, heartbeats, elastic epochs, journaling,
obs folding — is the battle-tested single-tracker machinery, unchanged;
the shard adds exactly three behaviours:

* **Ownership-checked admission.**  A registration for a job whose
  ring owner is another shard gets the typed ``REJECT_SHARD_MOVED``
  reply whose reason carries ``gen/shard/endpoint`` so the worker
  re-targets without a directory round trip.  A job already live here
  stays here until it finishes (sticky), so a mid-life membership
  change never strands a running job.
* **Journaled handoff.**  All shards share one ``--state-dir`` root.
  The generation-poll thread watches the directory; when a membership
  change hands this shard an arc whose previous owner is GONE from the
  fleet (the failover case), it replays the dead shard's job journals
  through the existing HA restore path.  While the replay runs, every
  racing submission gets the typed ``REJECT_REPLAYING`` backoff reject
  (linger-covered) — never a silent close, never a duplicate
  ``JobState`` on two shards.
* **Fleet-wide admission accounting.**  The caps live on the
  directory; each shard admits against the fleet totals from its last
  poll plus its own exact local counts, so rejects stay typed,
  stateless and deterministic given the polled snapshot.

A plain ``Tracker`` (no directory) remains the exact legacy
single-shard control plane — the wire is byte-identical both
directions, pinned by tests/test_shard.py.
"""
from __future__ import annotations

import os
import threading
import urllib.error

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu.tracker import protocol as P
from rabit_tpu.tracker.directory import (DirectoryClient,
                                         ring_from_snapshot)
from rabit_tpu.tracker.tracker import JobState, Tracker, _AdmissionReject
from rabit_tpu.utils.checks import log

DEFAULT_POLL_SEC = 0.5


class ShardServer(Tracker):
    """One shard among peers behind a job directory.

    ``directory`` is either a base URL (subprocess deployments — a
    :class:`DirectoryClient` is built over it) or an in-process
    :class:`Directory` authority (tests, ``rendezvous_storm --shards``).
    The shard registers itself at construction, adopts any journals it
    already owns, then keeps a poll thread reporting load and watching
    the generation."""

    def __init__(self, n_workers: int, host: str = "127.0.0.1",
                 port: int = 0, *, shard_index: int,
                 directory, poll_sec: float = DEFAULT_POLL_SEC,
                 state_dir: str | None = None, **kw) -> None:
        self._shard_index = int(shard_index)
        self._dir = (DirectoryClient(directory)
                     if isinstance(directory, str) else directory)
        self._poll_sec = max(float(poll_sec), 0.05)
        self._shard_lock = threading.Lock()
        self._snap: dict | None = None
        self._ring = None
        self._gen = -1
        self._prev_members: frozenset[int] = frozenset()
        self._last_reported = (0, 0)
        # Armed while adopted journals replay: _admit turns every
        # racing submission into the typed REJECT_REPLAYING.
        self._replay_gate = threading.Event()
        self._poll_stop = threading.Event()
        # The base restore path replays EVERY journal under state_dir —
        # correct for a lone tracker, wrong for one shard of a shared
        # root.  Construct without it, then adopt ownership-filtered.
        super().__init__(n_workers, host, port, state_dir=None, **kw)
        self._state_base = str(state_dir) if state_dir else None
        snap = self._dir.register(self._shard_index, self.host,
                                  self.port, self.obs_port or 0)
        self._adopt_snapshot(snap)
        self._adopt_owned_jobs(bootstrap=True)
        threading.Thread(target=self._poll_loop,
                         name=f"rabit-shard{self._shard_index}-poll",
                         daemon=True).start()

    # -- directory membership ------------------------------------------
    def _adopt_snapshot(self, snap: dict) -> bool:
        """Install a directory snapshot; True when the generation moved
        forward (membership changed — the ring must be rebuilt and an
        adoption pass considered)."""
        if not isinstance(snap, dict):
            return False
        gen = int(snap.get("generation", -1))
        with self._shard_lock:
            if gen < self._gen:
                return False
            if gen == self._gen:
                self._snap = snap  # fresher fleet counts, same ring
                return False
            self._prev_members = frozenset(
                s["index"] for s in (self._snap or {}).get("shards", ()))
            self._snap = snap
            self._gen = gen
            self._ring = ring_from_snapshot(snap)
            members = [s["index"] for s in snap.get("shards", ())]
        self._count("shard.generation")
        log("shard %d: directory generation %d (shards %s)",
            self._shard_index, gen, members)
        return True

    def _poll_loop(self) -> None:
        """Report load / learn the generation every ``poll_sec``.  The
        poll doubles as this shard's liveness beat; a directory outage
        is ridden out on the cached snapshot (admission keeps its last
        known ring — bounded staleness, never a stall)."""
        while not self._poll_stop.wait(self._poll_sec):
            with self._jobs_lock:
                active = [j for j in self._jobs.values()
                          if j.touched and not j.done]
                jobs = len(active)
                workers = sum(j.n_workers for j in active)
            try:
                snap = self._dir.poll(self._shard_index, jobs=jobs,
                                      workers=workers)
                self._last_reported = (jobs, workers)
                if self._shard_index not in {
                        s["index"] for s in snap.get("shards", ())}:
                    # Health-removed while alive (an obs hiccup), or a
                    # restarted directory: re-assert our membership.
                    snap = self._dir.register(
                        self._shard_index, self.host, self.port,
                        self.obs_port or 0)
            except (OSError, urllib.error.URLError, ValueError) as e:
                self._count("shard.poll_failures")
                log("shard %d: directory poll failed: %s",
                    self._shard_index, e)
                continue
            if self._adopt_snapshot(snap):
                self._adopt_owned_jobs()

    def stop(self) -> None:
        self._poll_stop.set()
        super().stop()

    # -- journaled handoff ---------------------------------------------
    def _owner(self, name: str) -> int | None:
        with self._shard_lock:
            ring = self._ring
        if ring is None:
            return None
        try:
            return ring.owner(name)
        except LookupError:
            return None

    def _restore_named_jobs(self) -> None:
        """Disabled for shards (state_dir is withheld from the base
        constructor anyway): all replay goes through the ownership-
        filtered :meth:`_adopt_owned_jobs`."""

    def _journal_names(self) -> list[str]:
        try:
            names = sorted(os.listdir(self._state_base))
        except OSError:
            return []
        return [n for n in names
                if n != P.DEFAULT_JOB and P.valid_job_id(n)
                and os.path.isdir(os.path.join(self._state_base, n))]

    def _adopt_owned_jobs(self, bootstrap: bool = False) -> None:
        """Replay journals for arcs this shard now owns.

        A journal is adopted when the current ring maps its job here
        AND its previous owner left the fleet (that shard's death is
        what moved the arc) — a membership GROWTH never re-replays a
        job that is still live on its sticky previous owner, which
        would be the duplicate-JobState bug.  ``bootstrap`` (first pass
        after registration, journals present = whole-fleet cold
        restart) adopts everything owned regardless of history.  The
        replay gate is armed for the whole pass: racing submissions
        get REJECT_REPLAYING, then retry into a consistent shard."""
        if not self._state_base:
            return
        with self._shard_lock:
            gen = self._gen
            prev = self._prev_members
            members = frozenset(
                s["index"] for s in (self._snap or {}).get("shards", ()))
        removed = prev - members
        if not bootstrap and not removed:
            return
        self._replay_gate.set()
        try:
            adopted = 0
            for name in self._journal_names():
                if self._owner(name) != self._shard_index:
                    continue
                with self._jobs_lock:
                    live = self._jobs.get(name)
                    if live is not None and not live.done:
                        continue  # already hosted here
                job = JobState(self, name, self._default_world)
                if self._obs_base:
                    job._obs_dir = os.path.join(self._obs_base, name)
                sub = os.path.join(self._state_base, name)
                try:
                    job.attach_store(ckpt_mod.CheckpointStore(
                        sub, rank=0, keep=3))
                except OSError as e:
                    log("shard %d: cannot open job %r journal: %s",
                        self._shard_index, name, e)
                    continue
                if job.restore_journal() and not job.done:
                    with self._jobs_lock:
                        self._jobs[name] = job
                    self._mark_restored(job)
                    adopted += 1
            # The default job journals at the state root; its arc moves
            # like any named job's.
            if self._owner(P.DEFAULT_JOB) == self._shard_index:
                default = self._default_job()
                if not default.touched and default._state_store is None:
                    try:
                        default.attach_store(ckpt_mod.CheckpointStore(
                            self._state_base, rank=0, keep=3))
                        if default.restore_journal() and not default.done:
                            self._mark_restored(default)
                            adopted += 1
                    except OSError as e:
                        log("shard %d: default job journal "
                            "unavailable: %s", self._shard_index, e)
            if adopted:
                self._count("shard.jobs_adopted", adopted)
                log("shard %d: adopted %d job journal(s) at "
                    "generation %d", self._shard_index, adopted, gen)
        finally:
            self._replay_gate.clear()

    # -- admission ------------------------------------------------------
    def _admit(self, name: str, world_hint: int) -> JobState:
        """Ownership + fleet capacity in front of the base admission.
        Every reject below raises BEFORE any job state exists — the
        same stateless contract as the base checks."""
        with self._shard_lock:
            gen, snap = self._gen, self._snap
        if self._replay_gate.is_set():
            raise _AdmissionReject(
                P.REJECT_REPLAYING, "replaying",
                f"job {name!r} refused: shard {self._shard_index} is "
                f"replaying adopted journals (generation {gen}); "
                "back off and retry")
        with self._jobs_lock:
            live = self._jobs.get(name)
            sticky = live is not None and not live.done
        if not sticky:
            # Admitting a NEW job on a stale ring is the duplicate-
            # JobState bug (two shards each believing they own it), so
            # new-job admission re-reads the authoritative snapshot —
            # one round trip, paid only on the rare job-creation path.
            # A directory outage falls back to the cached ring
            # (bounded staleness beats refusing all work).
            try:
                fresh = (self._dir.snapshot(refresh=True)
                         if isinstance(self._dir, DirectoryClient)
                         else self._dir.snapshot())
                if self._adopt_snapshot(fresh):
                    # The refresh revealed a membership change: adopt
                    # any newly-owned journals BEFORE admitting, or a
                    # handed-off job would be re-created fresh (its
                    # journal orphaned) inside the poll-tick window.
                    self._adopt_owned_jobs()
                with self._shard_lock:
                    gen, snap = self._gen, self._snap
            except (OSError, urllib.error.URLError, ValueError) as e:
                self._count("shard.refresh_failures")
                log("shard %d: admission-time directory refresh "
                    "failed (%s); using the cached ring",
                    self._shard_index, e)
            owner = self._owner(name)
            if owner is not None and owner != self._shard_index:
                endpoint = ("", 0)
                for s in (snap or {}).get("shards", ()):
                    if s["index"] == owner:
                        endpoint = (s["host"], s["port"])
                raise _AdmissionReject(
                    P.REJECT_SHARD_MOVED, "shard_moved",
                    P.shard_moved_reason(gen, owner, endpoint[0],
                                         endpoint[1]))
            self._check_fleet_capacity(name, world_hint, snap)
        return super()._admit(name, world_hint)

    def _check_fleet_capacity(self, name: str, world_hint: int,
                              snap: dict | None) -> None:
        """Fleet-wide ``--max-jobs``/``--max-total-workers`` (held by
        the directory).  Remote load is the fleet total from the last
        poll minus what this shard itself reported then; local load is
        exact.  Bounded staleness (one poll period), deterministic
        given the snapshot."""
        caps = (snap or {}).get("caps") or {}
        max_jobs = int(caps.get("max_jobs") or 0)
        max_workers = int(caps.get("max_total_workers") or 0)
        if not max_jobs and not max_workers:
            return
        fleet = (snap or {}).get("fleet") or {}
        rep_jobs, rep_workers = self._last_reported
        with self._jobs_lock:
            active = [j for j in self._jobs.values()
                      if j.touched and not j.done]
            local_jobs = len(active)
            local_workers = sum(j.n_workers for j in active)
        remote_jobs = max(int(fleet.get("jobs", 0)) - rep_jobs, 0)
        remote_workers = max(int(fleet.get("workers", 0)) - rep_workers,
                             0)
        world = (world_hint if world_hint > 0 and name != P.DEFAULT_JOB
                 else self._default_world)
        if max_jobs and remote_jobs + local_jobs >= max_jobs:
            raise _AdmissionReject(
                P.REJECT_MAX_JOBS, "jobs",
                f"job {name!r} refused: {remote_jobs + local_jobs} "
                f"active job(s) fleet-wide at the --max-jobs="
                f"{max_jobs} capacity; retry after one finishes")
        if max_workers and (remote_workers + local_workers + world
                            > max_workers):
            raise _AdmissionReject(
                P.REJECT_MAX_WORKERS, "workers",
                f"job {name!r} refused: {remote_workers + local_workers}"
                f" worker(s) active fleet-wide + {world} requested "
                f"exceeds --max-total-workers={max_workers}; retry "
                "after one finishes")

    def _service_done(self) -> bool:
        """A shard never self-retires.  The base tracker exits once
        every admitted job finished; a shard is one member of a
        long-lived fleet — the next submission may hash onto it at any
        moment, and its /status must stay scrapeable for the
        hierarchical fold after its last job closes.  Operator stop
        (:meth:`stop` / SIGTERM) ends it."""
        return False

    # -- obs ------------------------------------------------------------
    def _render_status(self) -> dict:
        out = super()._render_status()
        out["shard"] = self._shard_index
        with self._shard_lock:
            out["directory"] = {"generation": self._gen,
                                "shards": sorted(
                                    s["index"] for s in
                                    (self._snap or {}).get("shards", ()))}
        for row in out["jobs"].values():
            row.setdefault("shard", self._shard_index)
        return out

    def _render_http_extra(self, path: str) -> tuple[str, str] | None:
        """Mirror the latest directory snapshot on this shard's obs
        endpoint (``GET /directory``) — the directory is "served by
        every shard", so a client can bootstrap from any one of them."""
        if path == "/directory":
            import json
            with self._shard_lock:
                snap = self._snap
            if snap is None:
                return None
            return (json.dumps(snap, sort_keys=True),
                    "application/json")
        return super()._render_http_extra(path)

    def worker_env(self, task_id: str,
                   job: str | None = None) -> dict[str, str]:
        env = super().worker_env(task_id, job)
        if isinstance(self._dir, DirectoryClient):
            env["RABIT_DIRECTORY"] = self._dir.base_url
        return env
