"""Pallas TPU ring allreduce — explicit ICI ring with remote DMA.

The reference's allreduce is a pipelined tree over TCP with per-link ring
buffers and chunked streaming (reference: src/allreduce_base.cc:326-491,
ring buffers src/allreduce_base.h:256-295).  On TPU the same
bandwidth-optimal idea is a ring over the ICI torus: ``ndev - 1``
reduce-scatter hops followed by ``ndev - 1`` all-gather hops, each hop a
remote DMA to the right neighbour overlapping the VPU combine.  XLA's
built-in ``psum`` already schedules rings; this kernel is the explicit
version for cases XLA does not fuse well (very large payloads, custom
hop/compute overlap) and the blueprint for hand-scheduled collectives.

Flow control: the naive two-slot double buffer in a ring can be clobbered
when a sender runs more than two hops ahead of its right neighbour (the
progress chain around the ring only bounds the lead by ``ndev - 1``).
Each hop therefore acknowledges consumption: after folding slot ``s`` into
the accumulator the receiver signals the sender's capacity semaphore, and
a sender re-entering slot ``s`` first waits for that ack — the same
credit scheme the reference gets implicitly from TCP flow control on its
per-link ring buffers (reference: src/allreduce_base.cc:399-441).

Works under ``shard_map`` on a real TPU mesh, and on the CPU backend via
the distributed TPU interpreter (``pltpu.InterpretParams``) for tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from rabit_tpu.ops.reduce_ops import ReduceOp

_LOGICAL = pltpu.DeviceIdType.LOGICAL
_NSLOTS = 2

_COMBINE = {
    ReduceOp.SUM: jnp.add,
    ReduceOp.MAX: jnp.maximum,
    ReduceOp.MIN: jnp.minimum,
    ReduceOp.PROD: jnp.multiply,
}

# Budget for on-chip buffers: x + out + comm slots must fit VMEM with
# headroom (~16 MB/core).  Larger payloads are segmented by the wrapper.
_VMEM_BUDGET_BYTES = 8 << 20


def supported_ops():
    """Ops the ring kernel can combine (the engine's pallas_ring
    device-impl routes only these through the kernel)."""
    return frozenset(_COMBINE)


def _ring_kernel(x_ref, out_ref, comm_ref, send_sem, recv_sem, cap_sem,
                 *, ndev: int, combine, axis_name: str):
    """One full allreduce: reduce-scatter then all-gather on a ring.

    Refs: ``x_ref``/``out_ref`` are (ndev, chunk) in VMEM; ``comm_ref``
    is the (_NSLOTS, chunk) landing pad written by the left neighbour.
    """
    my_id = lax.axis_index(axis_name)
    right = lax.rem(my_id + 1, ndev)
    left = lax.rem(my_id + ndev - 1, ndev)

    out_ref[:] = x_ref[:]

    # Neighbour barrier: both sides' comm buffers must exist before any
    # remote DMA lands (guide pattern; collective_id scopes the sem).
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id=left,
                           device_id_type=_LOGICAL)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right,
                           device_id_type=_LOGICAL)
    pltpu.semaphore_wait(barrier, 2)

    nphase = ndev - 1  # hops per phase

    def hop(step, _):
        slot = lax.rem(step, _NSLOTS)
        is_rs = step < nphase
        s2 = step - nphase
        # reduce-scatter walks chunks backwards from my own; all-gather
        # then circulates the finished chunks (device i finishes chunk
        # (i+1) % ndev after the RS phase).
        send_idx = jnp.where(is_rs,
                             lax.rem(my_id - step + 2 * ndev, ndev),
                             lax.rem(my_id + 1 - s2 + 2 * ndev, ndev))
        recv_idx = jnp.where(is_rs,
                             lax.rem(my_id - step - 1 + 2 * ndev, ndev),
                             lax.rem(my_id - s2 + 2 * ndev, ndev))

        # credit: slot must have been drained by the right neighbour
        @pl.when(step >= _NSLOTS)
        def _():
            pltpu.semaphore_wait(cap_sem.at[slot], 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=out_ref.at[pl.ds(send_idx, 1)],
            dst_ref=comm_ref.at[pl.ds(slot, 1)],
            send_sem=send_sem.at[slot],
            recv_sem=recv_sem.at[slot],
            device_id=right,
            device_id_type=_LOGICAL,
        )
        rdma.start()
        rdma.wait()

        incoming = comm_ref[pl.ds(slot, 1), :]
        current = out_ref[pl.ds(recv_idx, 1), :]
        out_ref[pl.ds(recv_idx, 1), :] = jnp.where(
            is_rs, combine(current, incoming), incoming)

        # ack to the sender (my left neighbour): slot drained
        pltpu.semaphore_signal(cap_sem.at[slot], inc=1, device_id=left,
                               device_id_type=_LOGICAL)
        return 0

    lax.fori_loop(0, 2 * nphase, hop, 0)

    # Drain outstanding acks from the right neighbour so no semaphore is
    # left non-zero at kernel exit (the last _NSLOTS sends are never
    # re-entered, but their acks still arrive).
    def drain(slot, _):
        pltpu.semaphore_wait(cap_sem.at[slot], 1)
        return 0

    lax.fori_loop(0, min(_NSLOTS, 2 * nphase), drain, 0)


def _segment_allreduce(seg, axis_name, ndev, chunk, op, interpret,
                       collective_id):
    combine = _COMBINE[op]
    kern = functools.partial(_ring_kernel, ndev=ndev, combine=combine,
                             axis_name=axis_name)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((ndev, chunk), seg.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((_NSLOTS, chunk), seg.dtype),
            pltpu.SemaphoreType.DMA((_NSLOTS,)),
            pltpu.SemaphoreType.DMA((_NSLOTS,)),
            pltpu.SemaphoreType.REGULAR((_NSLOTS,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(seg)
    return out


def ring_allreduce_pallas(x: jax.Array, axis_name: str,
                          op: ReduceOp = ReduceOp.SUM,
                          interpret: bool | None = None,
                          collective_id: int = 7) -> jax.Array:
    """Allreduce ``x`` (same shape on every device) along ``axis_name``.

    Call inside ``shard_map``.  Pads the flattened payload to
    ``ndev × chunk`` with 128-aligned chunks, runs the ring kernel per
    VMEM-sized segment, and restores the original shape.  ``interpret``
    defaults to auto (True off-TPU so tests run on the CPU mesh).
    """
    if op not in _COMBINE:
        raise ValueError(f"ring_allreduce_pallas: unsupported op {op}")
    ndev = lax.axis_size(axis_name)
    if ndev == 1:
        return x
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    flat = x.reshape(-1)
    size = flat.shape[0]
    chunk = max(128, -(-size // ndev))
    chunk = -(-chunk // 128) * 128

    # segment so (x + out + slots) stays inside the VMEM budget
    bytes_per = ndev * chunk * flat.dtype.itemsize
    nseg = max(1, -(-2 * bytes_per // _VMEM_BUDGET_BYTES))
    seg_chunk = -(-chunk // (128 * nseg)) * 128
    nseg = -(-chunk // seg_chunk)

    padded = jnp.zeros((ndev * nseg * seg_chunk,), flat.dtype
                       ).at[:size].set(flat)
    segs = padded.reshape(ndev, nseg, seg_chunk)

    outs = []
    for s in range(nseg):
        outs.append(_segment_allreduce(
            segs[:, s, :], axis_name, ndev, seg_chunk, op, interpret,
            collective_id))
    out = jnp.stack(outs, axis=1).reshape(-1)[:size]
    return out.reshape(x.shape)
