"""Reduction operator and data-type registry.

Reference parity: op::Max/Min/Sum/BitOR (include/rabit/rabit-inl.h:55-92),
mpi::DataType/OpType enums (include/rabit/engine.h:169-186), and the numpy
dtype table in the Python wrapper (wrapper/rabit.py:171-180).

We extend the reference's {max,min,sum,bitor} set with prod/bitand/bitxor —
all of which lower directly onto XLA reductions — and register TPU-relevant
dtypes (bfloat16) that the reference predates.
"""
from __future__ import annotations

import enum
from typing import Callable

import numpy as np


class ReduceOp(enum.IntEnum):
    """Wire/ABI-stable reduction op codes (reference: include/rabit/engine.h:181-186)."""

    MAX = 0
    MIN = 1
    SUM = 2
    PROD = 3
    BITOR = 4
    BITAND = 5
    BITXOR = 6


MAX = ReduceOp.MAX
MIN = ReduceOp.MIN
SUM = ReduceOp.SUM
PROD = ReduceOp.PROD
BITOR = ReduceOp.BITOR
BITAND = ReduceOp.BITAND
BITXOR = ReduceOp.BITXOR


class DataType(enum.IntEnum):
    """Wire/ABI-stable dtype codes (reference: include/rabit/rabit-inl.h:17-52)."""

    INT8 = 0
    UINT8 = 1
    INT32 = 2
    UINT32 = 3
    INT64 = 4
    UINT64 = 5
    FLOAT32 = 6
    FLOAT64 = 7
    # TPU-era extensions (not in the reference):
    BFLOAT16 = 8
    FLOAT16 = 9


_NP_TO_ENUM: dict[str, DataType] = {
    "int8": DataType.INT8,
    "uint8": DataType.UINT8,
    "int32": DataType.INT32,
    "uint32": DataType.UINT32,
    "int64": DataType.INT64,
    "uint64": DataType.UINT64,
    "float32": DataType.FLOAT32,
    "float64": DataType.FLOAT64,
    "bfloat16": DataType.BFLOAT16,
    "float16": DataType.FLOAT16,
}

_ENUM_TO_NP: dict[DataType, str] = {v: k for k, v in _NP_TO_ENUM.items()}

_ITEMSIZE: dict[DataType, int] = {
    DataType.INT8: 1,
    DataType.UINT8: 1,
    DataType.INT32: 4,
    DataType.UINT32: 4,
    DataType.INT64: 8,
    DataType.UINT64: 8,
    DataType.FLOAT32: 4,
    DataType.FLOAT64: 8,
    DataType.BFLOAT16: 2,
    DataType.FLOAT16: 2,
}


def dtype_to_enum(dtype) -> DataType:
    """Map a numpy/jax dtype (or its name) to the wire enum."""
    name = np.dtype(dtype).name if not isinstance(dtype, str) else dtype
    if name not in _NP_TO_ENUM:
        raise TypeError(f"unsupported allreduce dtype: {name}")
    return _NP_TO_ENUM[name]


def enum_to_dtype(code: int):
    """Map a wire enum back to a numpy dtype (bfloat16 via ml_dtypes)."""
    name = _ENUM_TO_NP[DataType(code)]
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def itemsize(code: int) -> int:
    return _ITEMSIZE[DataType(code)]


_NUMPY_FNS: dict[ReduceOp, Callable] = {
    ReduceOp.MAX: np.maximum,
    ReduceOp.MIN: np.minimum,
    ReduceOp.SUM: np.add,
    ReduceOp.PROD: np.multiply,
    ReduceOp.BITOR: np.bitwise_or,
    ReduceOp.BITAND: np.bitwise_and,
    ReduceOp.BITXOR: np.bitwise_xor,
}


def apply_op_numpy(op: ReduceOp, dst: np.ndarray, src: np.ndarray) -> np.ndarray:
    """dst = dst OP src, elementwise, in place when possible.

    This is the host-side reducer used by the local/loopback paths; the
    native engine and XLA engine have their own reducers (C++ and XLA resp.).
    Reference analogue: op::Reducer (include/rabit/rabit-inl.h:84-91).
    """
    fn = _NUMPY_FNS[ReduceOp(op)]
    return fn(dst, src, out=dst) if dst.flags.writeable else fn(dst, src)


def apply_op_pairwise(op: ReduceOp, a, b):
    """Elementwise a OP b on device (the XLA-side reducer, jax arrays)."""
    import jax.numpy as jnp

    table = {
        ReduceOp.MAX: jnp.maximum,
        ReduceOp.MIN: jnp.minimum,
        ReduceOp.SUM: jnp.add,
        ReduceOp.PROD: jnp.multiply,
        ReduceOp.BITOR: jnp.bitwise_or,
        ReduceOp.BITAND: jnp.bitwise_and,
        ReduceOp.BITXOR: jnp.bitwise_xor,
    }
    return table[ReduceOp(op)](a, b)


def apply_op_jax(op: ReduceOp, x, axis_name: str):
    """Lower a reduce op onto the matching XLA collective inside shard_map/pmap."""
    import functools

    import jax

    table = {
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
        ReduceOp.SUM: jax.lax.psum,
    }
    ropx = ReduceOp(op)
    if ropx in table:
        return table[ropx](x, axis_name)
    # prod / bitwise ops have no dedicated collective: all-gather then reduce
    # locally (XLA fuses this; payloads for these ops are small flag words).
    gathered = jax.lax.all_gather(x, axis_name)
    return functools.reduce(
        functools.partial(apply_op_pairwise, ropx),
        [gathered[i] for i in range(gathered.shape[0])])
