"""Fused Pallas kernel for the XGBoost gradient-histogram pass.

Measured with chained difference timing (the only honest method
through the tunneled chip — independent dispatches don't serialize and
block_until_ready doesn't block, doc/benchmarks.md): the XLA one-hot
formulation takes ~30 ms for 262k x 64 x 256 (N=2 output lanes leave
the MXU ~2% occupied); this kernel runs the same histogram in ~0.8 ms
(~37x) at ~100% MXU occupancy of its fpg-fold-inflated FLOPs, and
generalizes to an (nw, n) weight matrix (any number of grad/hess/node
channels) that builds every channel's histogram in ONE bins pass: the
bin one-hots are built once per feature group and contracted against
each weight row, so a GBDT tree level costs ~0.4 ms per channel
instead of a 30 ms XLA pass per node.

MXU structure (per feature group, per row block):

* **Two-level bins.**  Split each bin index ``b`` into ``b = bh*lo+bl``
  (``hi`` x ``lo``, powers of two, e.g. 16x16 for 256 bins).  The
  histogram of feature ``j`` is an outer product of two small one-hots:
  ``hist_j[bh, bl] = sum_r w_r * [hi_rj==bh] * [lo_rj==bl]``.
* **Feature packing.**  Stack ``fpg = 128//lo`` features' hi-one-hots
  along M and lo-one-hots along N: ``C = (A*w) @ B^T`` with A
  ``(fpg*hi, block)``, B ``(fpg*lo, block)`` -> C ``(fpg*hi, fpg*lo)``.
  Only the diagonal feature blocks of C are wanted (cross-feature
  terms are discarded), an ``fpg``-fold compute inflation — but at
  ~100% MXU tile occupancy, far better than the N=2 exact formulation.
* **Layout.**  Both one-hots are built directly in transposed
  ``(class, row)`` layout from a pre-transposed ``(f, n)`` bins array
  (broadcast-iota compare; the kmeans-kernel lesson — never relayout
  inside the kernel), and the matmul is the MXU-native NT form.  The
  raw per-group C products are accumulated in VMEM across row blocks;
  the cheap diagonal-block extraction runs in XLA afterwards.

Like the kmeans kernel the weight operand is rounded to a compute
dtype (default bf16; one-hots are exact in bf16).  Summing n values
each with independent ~2^-9 relative rounding error gives a relative
error on a bin sum of ~2^-9/sqrt(n_bin) — invisible to split-gain
comparisons except at exactly-cancelling bins, where the absolute
error is what matters and stays tiny.  ``compute_dtype=float32`` uses
``Precision.HIGHEST`` (the MXU rounds f32 matmul operands to bf16 at
default precision) for an exact path at ~3x the MXU cost.

Reference analogue: the histogram allreduce is the headline XGBoost
config in BASELINE.md; the reference ships only the collective
(reference: src/allreduce_base.cc) — the builder itself is the app's
job, done here the TPU way.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM_LIMIT_BYTES = 100 << 20
_DEFAULT_BLOCK = 2048
_MAX_CHANNELS = 64


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def _next_pow2(v: int) -> int:
    p = 1
    while p < v:
        p <<= 1
    return p


def max_channels(nbin: int, f: int) -> int:
    """Largest weight-channel count whose (ngroups, nw, fpg*hi, fpg*lo)
    f32 VMEM accumulator fits the kernel's budget for this shape —
    level builders derive their chunk size from this instead of a fixed
    constant, so wide-feature deep levels chunk harder rather than
    failing the accumulator bound."""
    hi, lo, fpg, ngroups = plan(nbin, f)
    per_channel = ngroups * fpg * hi * fpg * lo * 4
    return max(1, min(_MAX_CHANNELS,
                      (_VMEM_LIMIT_BYTES // 2) // per_channel))


def plan(nbin: int, f: int):
    """(hi, lo, fpg, ngroups) decomposition for an (f, nbin) histogram.

    ``hi*lo`` is ``nbin`` padded to a power of two with ``hi >= lo``;
    ``fpg = 128 // lo`` features share one matmul so the N dimension
    fills 128 lanes exactly.
    """
    nbp = _next_pow2(max(nbin, 4))
    bits = nbp.bit_length() - 1
    lo = 1 << (bits // 2)
    hi = nbp // lo
    fpg = max(1, 128 // lo)
    ngroups = -(-f // fpg)
    return hi, lo, fpg, ngroups


def _hist_kernel(bins_t_ref, w_ref, out_ref, *,
                 hi: int, lo: int, fpg: int, ngroups: int, nw: int):
    i = pl.program_id(0)
    block = w_ref.shape[1]
    w = w_ref[:]                                   # (nw, block) compute dtype
    lo_shift = lo.bit_length() - 1
    lo_mask = lo - 1
    cdt = w.dtype
    prec = (lax.Precision.HIGHEST if cdt == jnp.float32
            else lax.Precision.DEFAULT)

    groups = []
    for grp in range(ngroups):
        bt = bins_t_ref[grp * fpg:(grp + 1) * fpg, :]        # (fpg, block)
        bh = lax.shift_right_logical(bt, lo_shift)
        bl = lax.bitwise_and(bt, lo_mask)
        # one-hots built once per group in (class, row) layout, shared
        # by every weight channel — no relayout, no extra HBM traffic
        hi_iota = lax.broadcasted_iota(jnp.int32, (fpg, hi, block), 1)
        a = (bh[:, None, :] == hi_iota).astype(cdt)
        a = a.reshape(fpg * hi, block)                       # (M, block)
        lo_iota = lax.broadcasted_iota(jnp.int32, (fpg, lo, block), 1)
        b = (bl[:, None, :] == lo_iota).astype(cdt)
        b = b.reshape(fpg * lo, block)                       # (N, block)
        cs = []
        for c in range(nw):
            # MXU-native NT matmul: contract over the row dimension
            cs.append(lax.dot_general(
                a * w[c:c + 1, :], b, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec))
        groups.append(jnp.stack(cs))          # (nw, fpg*hi, fpg*lo)
    contrib = jnp.stack(groups)               # (ngroups, nw, ...)

    @pl.when(i == 0)
    def _():
        out_ref[:] = contrib

    @pl.when(i != 0)
    def _():
        out_ref[:] = out_ref[:] + contrib


@functools.partial(
    jax.jit,
    static_argnames=("nbin", "block", "interpret", "compute_dtype",
                     "plan_override"))
def _hist_multi(bins_t, weights, nbin: int, block: int,
                interpret: bool, compute_dtype,
                plan_override=None) -> jax.Array:
    f, n = bins_t.shape
    nw = weights.shape[0]
    if plan_override is None:
        hi, lo, fpg, ngroups = plan(nbin, f)
    else:
        hi, lo, fpg = plan_override
        if hi * lo < nbin:
            raise ValueError(f"plan {plan_override}: hi*lo < nbin={nbin}")
        if lo & (lo - 1):
            # the kernel decomposes bins with shift/mask — a non-pow2 lo
            # would silently scatter counts into wrong bins
            raise ValueError(f"plan {plan_override}: lo must be a "
                             "power of two")
        ngroups = -(-f // fpg)
    # The whole (ngroups, nw, fpg*hi, fpg*lo) f32 accumulator is one
    # VMEM-resident output block: validate the combined bound up front
    # (wide-feature many-node levels can exceed it) with a clear error
    # instead of a compile-time OOM.
    out_bytes = ngroups * nw * fpg * hi * fpg * lo * 4
    if out_bytes > _VMEM_LIMIT_BYTES // 2:
        raise ValueError(
            f"histogram accumulator needs {out_bytes >> 20} MB of VMEM "
            f"(ngroups={ngroups} x nw={nw} x {fpg * hi} x {fpg * lo} f32) "
            f"> {(_VMEM_LIMIT_BYTES // 2) >> 20} MB budget — chunk the "
            "channels (build_level_local does) or the features across "
            "calls")
    fpad = ngroups * fpg
    npad = _round_up(n, block)
    cdt = jnp.dtype(compute_dtype)

    bt = jnp.pad(bins_t.astype(jnp.int32),
                 ((0, fpad - f), (0, npad - n)))
    w = jnp.pad(weights.astype(cdt), ((0, 0), (0, npad - n)))

    params = pltpu.CompilerParams(
        dimension_semantics=("arbitrary",),
        vmem_limit_bytes=_VMEM_LIMIT_BYTES)
    raw = pl.pallas_call(
        functools.partial(_hist_kernel, hi=hi, lo=lo, fpg=fpg,
                          ngroups=ngroups, nw=nw),
        grid=(npad // block,),
        in_specs=[
            pl.BlockSpec((fpad, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nw, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (ngroups, nw, fpg * hi, fpg * lo), lambda i: (0, 0, 0, 0),
            memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (ngroups, nw, fpg * hi, fpg * lo), jnp.float32),
        compiler_params=params,
        interpret=interpret,
    )(bt, w)

    # diagonal-block extraction (tiny, plain XLA): feature j of group g,
    # channel c lives at raw[g, c, j*hi:(j+1)*hi, j*lo:(j+1)*lo]
    c = raw.reshape(ngroups, nw, fpg, hi, fpg, lo)
    idx = jnp.arange(fpg)
    diag = c[:, :, idx, :, idx, :]         # (fpg, ngroups, nw, hi, lo)
    diag = diag.transpose(2, 1, 0, 3, 4)   # (nw, ngroups, fpg, hi, lo)
    return diag.reshape(nw, fpad, hi * lo)[:, :f, :nbin]


def default_block(n: int) -> int:
    """Row-block size: 2048 saturates the MXU pipeline; shrink for
    small inputs so padding stays bounded."""
    return min(_DEFAULT_BLOCK, _round_up(max(n, 1), 128))


def hist_fused_multi(bins_t, weights, nbin: int, block: int | None = None,
                     interpret: bool | None = None,
                     compute_dtype=jnp.bfloat16,
                     plan_override: tuple | None = None) -> jax.Array:
    """(nw, f, nbin) histograms of ``nw`` weight channels in one pass.

    ``bins_t`` is the TRANSPOSED (f, n) int32 bins array (the layout
    the kernel streams; keep it resident on device across calls —
    boosting reuses it for every node, level and round).  ``weights``
    is (nw, n); each row gets its own (f, nbin) histogram.  Extra
    channels share the single bins read, so per-level node histograms
    cost one HBM pass instead of one per node.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    f, n = bins_t.shape
    nw = weights.shape[0]
    if not 1 <= nw <= _MAX_CHANNELS:
        raise ValueError(f"nw={nw} out of range [1, {_MAX_CHANNELS}]")
    if block is None:
        block = default_block(n)
    block = min(block, _round_up(n, 128))
    return _hist_multi(jnp.asarray(bins_t), jnp.asarray(weights),
                       nbin, block, interpret,
                       jnp.dtype(compute_dtype).name,
                       plan_override=plan_override)


def hist_fused(bins, grad, hess, nbin: int, block: int | None = None,
               interpret: bool | None = None,
               compute_dtype=jnp.bfloat16) -> jax.Array:
    """(f, nbin, 2) gradient/hessian histogram of binned features.

    ``bins`` is (n, f) int32 in [0, nbin); ``grad``/``hess`` are (n,)
    weights.  Convenience wrapper over :func:`hist_fused_multi` with
    two channels (transposes ``bins`` internally — callers with the
    (f, n) layout at hand should call the multi variant directly).
    """
    bins = jnp.asarray(bins)
    w = jnp.stack([jnp.asarray(grad), jnp.asarray(hess)])
    out = hist_fused_multi(bins.T, w, nbin, block=block,
                           interpret=interpret,
                           compute_dtype=compute_dtype)
    return out.transpose(1, 2, 0)
