"""Reduction operators and dtype tables.

TPU-native equivalent of the reference's op/dtype enums
(reference: include/rabit/rabit-inl.h:17-92 — dtype→enum map and the
op::Max/Min/Sum/BitOR reducer structs; include/rabit/engine.h:169-186).
"""
from rabit_tpu.ops.reduce_ops import (
    ReduceOp,
    MAX,
    MIN,
    SUM,
    PROD,
    BITOR,
    BITAND,
    BITXOR,
    DataType,
    dtype_to_enum,
    enum_to_dtype,
    apply_op_numpy,
    apply_op_jax,
    apply_op_pairwise,
)

__all__ = [
    "ReduceOp",
    "MAX",
    "MIN",
    "SUM",
    "PROD",
    "BITOR",
    "BITAND",
    "BITXOR",
    "DataType",
    "dtype_to_enum",
    "enum_to_dtype",
    "apply_op_numpy",
    "apply_op_jax",
    "apply_op_pairwise",
]
