"""Fused Pallas kernel for the k-means cluster-statistics pass.

The reference computes per-point assignment and cluster sums in a C++
row loop on the host (reference: rabit-learn/kmeans/kmeans.cc:121-140).
The XLA version in :mod:`rabit_tpu.learn.kmeans` is two MXU matmuls with
an argmax between them, but XLA materialises the similarity and one-hot
intermediates in HBM (~2 extra payload-sized round trips).  This kernel
fuses the whole pass: each grid step loads one row block into VMEM,
computes similarity (MXU), argmax + one-hot compare (VPU), and folds the
block's (k, d) sums and (k,) counts into VMEM accumulators — data is
read from HBM exactly once.

Layout requirements (callers pad): ``d`` a multiple of 128 (lanes),
``k`` a multiple of 8 (sublanes), rows a multiple of the block size.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 2048


def _stats_kernel(x_ref, cn_ref, valid_ref, sums_ref, counts_ref,
                  *, k_real: int):
    i = pl.program_id(0)
    x = x_ref[:]                                  # (block, d)
    block, _ = x.shape
    k = cn_ref.shape[0]

    sim = jnp.dot(x, cn_ref[:].T,
                  preferred_element_type=jnp.float32)   # (block, k) MXU
    # padded centroid rows (zero vectors) would win the argmax whenever
    # every real similarity is negative — mask them out
    if k_real < k:
        col_ids = lax.broadcasted_iota(jnp.int32, (block, k), 1)
        sim = jnp.where(col_ids < k_real, sim, -jnp.inf)
    assign = jnp.argmax(sim, axis=1)                    # (block,)
    cols = lax.broadcasted_iota(jnp.int32, (block, k), 1)
    onehot = (cols == assign[:, None]).astype(jnp.float32)
    onehot = onehot * valid_ref[:]                      # mask padded rows

    # contract over rows without an explicit transpose (relayouts are
    # not free on TPU): (block, k) x (block, d) -> (k, d)
    part_sums = lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)              # (k, d) MXU
    part_counts = jnp.sum(onehot, axis=0)[None, :]           # (1, k)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = part_sums
        counts_ref[:] = part_counts

    @pl.when(i != 0)
    def _():
        sums_ref[:] = sums_ref[:] + part_sums
        counts_ref[:] = counts_ref[:] + part_counts


@functools.partial(jax.jit,
                   static_argnames=("block", "interpret", "k_real"))
def _stats_call(cnorm, x, valid, block: int, interpret: bool, k_real: int):
    n, d = x.shape
    k = cnorm.shape[0]
    nb = n // block
    sums, counts = pl.pallas_call(
        functools.partial(_stats_kernel, k_real=k_real),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
        ),
        interpret=interpret,
    )(x, cnorm, valid.reshape(n, 1))
    return sums, counts


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def kmeans_stats_fused(centroids: jax.Array, x: jax.Array,
                       valid: jax.Array, block: int = DEFAULT_BLOCK,
                       interpret: bool | None = None) -> jax.Array:
    """(k, d+1) stats matrix (counts in the last column) for dense rows.

    ``centroids`` (k, d) are L2-normalised internally (cosine distance,
    reference: kmeans.cc:63-79); ``x`` is (n, d) dense rows with invalid
    rows arbitrary, ``valid`` (n,) 1/0.  Pads k/d/n to hardware tiles,
    slices the result back.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k, d = centroids.shape
    n = x.shape[0]
    kp, dp = _round_up(k, 8), _round_up(d, 128)
    block = min(block, _round_up(n, 8))
    npad = _round_up(n, block)

    cnorm = centroids / (
        jnp.linalg.norm(centroids, axis=1, keepdims=True) + 1e-12)
    cnorm = jnp.pad(cnorm.astype(jnp.float32),
                    ((0, kp - k), (0, dp - d)))
    xp = jnp.pad(x.astype(jnp.float32), ((0, npad - n), (0, dp - d)))
    vp = jnp.pad(valid.astype(jnp.float32), (0, npad - n))

    sums, counts = _stats_call(cnorm, xp, vp, block, interpret, k)
    stats = jnp.concatenate([sums[:k, :d], counts[0, :k, None]], axis=1)
    return stats
