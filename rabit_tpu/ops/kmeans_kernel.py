"""Fused Pallas kernel for the k-means cluster-statistics pass.

The reference computes per-point assignment and cluster sums in a C++
row loop on the host (reference: rabit-learn/kmeans/kmeans.cc:121-140).
The XLA version in :mod:`rabit_tpu.learn.kmeans` is two MXU matmuls with
an argmax between them — each matmul streams the row data from HBM, so
the pass reads the payload twice.  This kernel fuses the whole pass:
each grid step loads one row block into VMEM, computes similarity
(MXU), argmax (VPU), builds the one-hot matrix *already transposed* as
(k, block), and folds the block's (k, d) sums and (k,) counts into VMEM
accumulators — data is read from HBM exactly once.

Two layout lessons measured on v5e (difference-timed to cancel the
axon-tunnel round trip, see doc/benchmarks.md):

* Building the one-hot as (block, k) and contracting over dim 0 forces
  a (block, k) -> (k, block) relayout inside the kernel every grid step
  (~4x slowdown).  Building it transposed makes both matmuls
  natural-layout: ``x @ cn.T`` and ``onehot_t @ x``.
* Block size 16384 with a raised scoped-VMEM limit saturates HBM
  (~860 GB/s in bf16); the 2048-row default of the old kernel left the
  DMA pipeline latency-bound.

Layout requirements (callers pad): ``d`` a multiple of 128 (lanes),
``k`` a multiple of 8 (sublanes), rows a multiple of the block size.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Per-block VMEM footprint target: the block plus its double-buffer
# partner should stay well under the raised scoped-VMEM limit.
_BLOCK_BYTES_TARGET = 8 << 20
_VMEM_LIMIT_BYTES = 100 << 20
_MAX_BLOCK = 16384


def _stats_kernel(x_ref, cn_ref, valid_ref, sums_ref, counts_ref,
                  *, k_real: int):
    i = pl.program_id(0)
    x = x_ref[:]                                  # (block, d), compute dtype
    block, _ = x.shape
    k = cn_ref.shape[0]

    sim = jnp.dot(x, cn_ref[:].T,
                  preferred_element_type=jnp.float32)   # (block, k) MXU
    # padded centroid rows (zero vectors) would win the argmax whenever
    # every real similarity is negative — mask them out
    if k_real < k:
        col_ids = lax.broadcasted_iota(jnp.int32, (block, k), 1)
        sim = jnp.where(col_ids < k_real, sim, -jnp.inf)
    assign = jnp.argmax(sim, axis=1)                    # (block,)
    # one-hot built directly in (k, block) layout: both dots are then
    # natural-layout matmuls and Mosaic inserts no relayout
    rows = lax.broadcasted_iota(jnp.int32, (k, block), 0)
    onehot_t = (rows == assign[None, :]).astype(jnp.float32)
    onehot_t = onehot_t * valid_ref[:]                  # (1, block) bcast

    part_sums = jnp.dot(onehot_t.astype(x.dtype), x,
                        preferred_element_type=jnp.float32)  # (k, d) MXU
    part_counts = jnp.sum(onehot_t, axis=1)[:, None]         # (k, 1)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = part_sums
        counts_ref[:] = part_counts

    @pl.when(i != 0)
    def _():
        sums_ref[:] = sums_ref[:] + part_sums
        counts_ref[:] = counts_ref[:] + part_counts


@functools.partial(jax.jit,
                   static_argnames=("block", "interpret", "k_real"))
def _stats_call(cnorm, x, valid, block: int, interpret: bool, k_real: int):
    n, d = x.shape
    k = cnorm.shape[0]
    nb = n // block
    params = pltpu.CompilerParams(
        dimension_semantics=("arbitrary",),
        vmem_limit_bytes=_VMEM_LIMIT_BYTES)
    sums, counts = pl.pallas_call(
        functools.partial(_stats_kernel, k_real=k_real),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k, 1), jnp.float32),
        ),
        compiler_params=params,
        interpret=interpret,
    )(x, cnorm, valid.reshape(1, n))
    return sums, counts


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def default_block(n: int, d: int, itemsize: int = 2) -> int:
    """Largest power-of-two row block whose VMEM footprint stays within
    the target budget (16384 rows at d=256 bf16 saturates HBM), shrunk
    further while rounding ``n`` up to the block would waste more than
    ~25% of the pass on padded rows."""
    block = _MAX_BLOCK
    while block > 512 and block * _round_up(d, 128) * itemsize \
            > _BLOCK_BYTES_TARGET:
        block //= 2
    while block > 1024 and (_round_up(n, block) - n) * 4 > n:
        block //= 2
    return block


def kmeans_stats_fused(centroids: jax.Array, x: jax.Array,
                       valid: jax.Array, block: int | None = None,
                       interpret: bool | None = None) -> jax.Array:
    """(k, d+1) stats matrix (counts in the last column) for dense rows.

    ``centroids`` (k, d) are L2-normalised internally (cosine distance,
    reference: kmeans.cc:63-79); ``x`` is (n, d) dense rows with invalid
    rows arbitrary, ``valid`` (n,) 1/0.  The similarity pass runs in
    ``x``'s dtype (bf16 halves the single HBM read); accumulation is
    always float32.  Pads k/d/n to hardware tiles, slices the result
    back.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k, d = centroids.shape
    n = x.shape[0]
    cdt = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    kp, dp = _round_up(k, 8), _round_up(d, 128)
    if block is None:
        block = default_block(n, d, jnp.dtype(cdt).itemsize)
    block = min(block, _round_up(n, 8))
    npad = _round_up(n, block)

    cnorm = centroids.astype(jnp.float32)
    cnorm = cnorm / (jnp.linalg.norm(cnorm, axis=1, keepdims=True) + 1e-12)
    cnorm = jnp.pad(cnorm.astype(cdt), ((0, kp - k), (0, dp - d)))
    xp = jnp.pad(x.astype(cdt), ((0, npad - n), (0, dp - d)))
    vp = jnp.pad(valid.astype(jnp.float32), (0, npad - n))

    sums, counts = _stats_call(cnorm, xp, vp, block, interpret, k)
    stats = jnp.concatenate([sums[:k, :d], counts[:k]], axis=1)
    return stats
