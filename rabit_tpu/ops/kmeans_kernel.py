"""Fused Pallas kernel for the k-means cluster-statistics pass.

The reference computes per-point assignment and cluster sums in a C++
row loop on the host (reference: rabit-learn/kmeans/kmeans.cc:121-140).
The XLA version in :mod:`rabit_tpu.learn.kmeans` is two MXU matmuls with
an argmax between them — each matmul streams the row data from HBM, so
the pass reads the payload twice.  This kernel fuses the whole pass:
each grid step loads one row block into VMEM, computes similarity
(MXU), argmax (VPU), builds the one-hot matrix *already transposed* as
(k, block), and folds the block's (k, d) sums and (k,) counts into VMEM
accumulators — data is read from HBM exactly once.

Two layout lessons measured on v5e (difference-timed to cancel the
axon-tunnel round trip, see doc/benchmarks.md):

* Building the one-hot as (block, k) and contracting over dim 0 forces
  a (block, k) -> (k, block) relayout inside the kernel every grid step
  (~4x slowdown).  Building it transposed makes both matmuls
  natural-layout: ``x @ cn.T`` and ``onehot_t @ x``.
* Block size 16384 with a raised scoped-VMEM limit saturates HBM
  (~860 GB/s in bf16); the 2048-row default of the old kernel left the
  DMA pipeline latency-bound.

Layout requirements (callers pad): ``d`` a multiple of 128 (lanes),
``k`` a multiple of 8 (sublanes), rows a multiple of the block size.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Per-block VMEM footprint target: the block plus its double-buffer
# partner should stay well under the raised scoped-VMEM limit.
_BLOCK_BYTES_TARGET = 8 << 20
_VMEM_LIMIT_BYTES = 100 << 20
_MAX_BLOCK = 16384


def _stats_kernel(x_ref, cn_ref, valid_ref, sums_ref, counts_ref,
                  *, k_real: int):
    i = pl.program_id(0)
    x = x_ref[:]                                  # (block, d), compute dtype
    block, _ = x.shape
    k = cn_ref.shape[0]

    sim = jnp.dot(x, cn_ref[:].T,
                  preferred_element_type=jnp.float32)   # (block, k) MXU
    # padded centroid rows (zero vectors) would win the argmax whenever
    # every real similarity is negative — mask them out
    if k_real < k:
        col_ids = lax.broadcasted_iota(jnp.int32, (block, k), 1)
        sim = jnp.where(col_ids < k_real, sim, -jnp.inf)
    assign = jnp.argmax(sim, axis=1)                    # (block,)
    # one-hot built directly in (k, block) layout: both dots are then
    # natural-layout matmuls and Mosaic inserts no relayout
    rows = lax.broadcasted_iota(jnp.int32, (k, block), 0)
    onehot_t = (rows == assign[None, :]).astype(jnp.float32)
    onehot_t = onehot_t * valid_ref[:]                  # (1, block) bcast

    part_sums = jnp.dot(onehot_t.astype(x.dtype), x,
                        preferred_element_type=jnp.float32)  # (k, d) MXU
    part_counts = jnp.sum(onehot_t, axis=1)[:, None]         # (k, 1)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = part_sums
        counts_ref[:] = part_counts

    @pl.when(i != 0)
    def _():
        sums_ref[:] = sums_ref[:] + part_sums
        counts_ref[:] = counts_ref[:] + part_counts


@functools.partial(jax.jit,
                   static_argnames=("block", "interpret", "k_real"))
def _stats_call(cnorm, x, valid, block: int, interpret: bool, k_real: int):
    n, d = x.shape
    k = cnorm.shape[0]
    nb = n // block
    params = pltpu.CompilerParams(
        dimension_semantics=("arbitrary",),
        vmem_limit_bytes=_VMEM_LIMIT_BYTES)
    sums, counts = pl.pallas_call(
        functools.partial(_stats_kernel, k_real=k_real),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k, 1), jnp.float32),
        ),
        compiler_params=params,
        interpret=interpret,
    )(x, cnorm, valid.reshape(1, n))
    return sums, counts


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def default_block(n: int, d: int, itemsize: int = 2) -> int:
    """Largest power-of-two row block whose VMEM footprint stays within
    the target budget (16384 rows at d=256 bf16 saturates HBM), shrunk
    further while rounding ``n`` up to the block would waste more than
    ~25% of the pass on padded rows."""
    block = _MAX_BLOCK
    while block > 512 and block * _round_up(d, 128) * itemsize \
            > _BLOCK_BYTES_TARGET:
        block //= 2
    while block > 1024 and (_round_up(n, block) - n) * 4 > n:
        block //= 2
    return block


def _ell_stats_kernel(idx_ref, val_ref, valid_ref, cn_ref,
                      sums_ref, counts_ref, *,
                      k_real: int, group: int, hi: int, lo: int,
                      nnz: int, compute_dtype):
    """Fused ELL stats step: two-level band densify (MXU) + similarity
    + argmax + one-hot stats, with the dense block living only in VMEM.

    Inputs arrive GROUPED: ``idx``/``val`` are (Bg, G·nnz) — G original
    rows per sublane row, so the band densify is one batched matmul.
    With the feature split ``f = (f // hi)·hi + (f % hi)`` the per-group
    matmul output (G·lo, hi) flattens row-major to G dense rows in
    natural feature order — no transpose, no relayout beyond the
    reshape.  Pad slots (index d, value 0) contribute zero because the
    weighted lo one-hot carries the value.

    Layout law (measured, this file's docstring + histogram_kernel.py):
    one-hots must be built with the DATA dimension in lanes and the
    class dimension in sublanes — the opposite orientation costs ~15x
    (a 3D (rows, slots, class) build measured 29 ms/pass vs sub-ms for
    this (batch, class, slots) form).  The batched densify contraction
    is therefore the MXU-native NT form (contraction dim = lanes of
    both operands)."""
    i = pl.program_id(0)
    idx = idx_ref[:]                              # (Bg, G*nnz) int32
    val = val_ref[:]                              # (Bg, G*nnz)
    bg = idx.shape[0]
    block = bg * group
    d = hi * lo
    k = cn_ref.shape[0]

    hi_bits = hi.bit_length() - 1
    hi_idx = lax.bitwise_and(idx, hi - 1)[:, None, :]   # (Bg, 1, P)
    # position p in [0, G*nnz) belongs to group-row g = p // nnz, whose
    # band is columns [g*lo, (g+1)*lo)
    g_of_p = lax.shift_right_logical(
        lax.broadcasted_iota(jnp.int32, (bg, 1, group * nnz), 2),
        nnz.bit_length() - 1)
    col = g_of_p * lo + lax.shift_right_logical(idx, hi_bits)[:, None, :]
    hio = (hi_idx ==
           lax.broadcasted_iota(jnp.int32, (bg, hi, group * nnz), 1)
           ).astype(compute_dtype)                # (Bg, hi, P)
    loo = ((col ==
            lax.broadcasted_iota(
                jnp.int32, (bg, group * lo, group * nnz), 1))
           * val[:, None, :]).astype(compute_dtype)  # (Bg, G*lo, P)
    dense3 = lax.dot_general(
        loo, hio, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)       # (Bg, G*lo, hi)
    dense = dense3.reshape(block, d).astype(compute_dtype)

    sim = jnp.dot(dense, cn_ref[:].T,
                  preferred_element_type=jnp.float32)   # (block, k) MXU
    if k_real < k:
        col_ids = lax.broadcasted_iota(jnp.int32, (block, k), 1)
        sim = jnp.where(col_ids < k_real, sim, -jnp.inf)
    assign = jnp.argmax(sim, axis=1)
    rows = lax.broadcasted_iota(jnp.int32, (k, block), 0)
    onehot_t = (rows == assign[None, :]).astype(jnp.float32)
    onehot_t = onehot_t * valid_ref[:]                  # (1, block) bcast

    part_sums = jnp.dot(onehot_t.astype(compute_dtype), dense,
                        preferred_element_type=jnp.float32)  # (k, d)
    part_counts = jnp.sum(onehot_t, axis=1)[:, None]         # (k, 1)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = part_sums
        counts_ref[:] = part_counts

    @pl.when(i != 0)
    def _():
        sums_ref[:] = sums_ref[:] + part_sums
        counts_ref[:] = counts_ref[:] + part_counts


@functools.partial(jax.jit, static_argnames=(
    "d", "group", "hi", "block", "interpret", "k_real", "compute_dtype"))
def _ell_stats_call(cnorm, idx_g, val_g, valid, d: int, group: int,
                    hi: int, block: int, interpret: bool, k_real: int,
                    compute_dtype):
    n_g, p = idx_g.shape
    nnz = p // group
    n = n_g * group
    k = cnorm.shape[0]
    bg = block // group
    nb = n // block
    lo = d // hi
    params = pltpu.CompilerParams(
        dimension_semantics=("arbitrary",),
        vmem_limit_bytes=_VMEM_LIMIT_BYTES)
    kernel = functools.partial(
        _ell_stats_kernel, k_real=k_real, group=group, hi=hi, lo=lo,
        nnz=nnz, compute_dtype=jnp.dtype(compute_dtype))
    sums, counts = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((bg, p), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bg, p), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((k, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k, 1), jnp.float32),
        ),
        compiler_params=params,
        interpret=interpret,
    )(idx_g, val_g, valid.reshape(1, n), cnorm)
    return sums, counts


def kmeans_ell_stats_fused(centroids: jax.Array, idx: jax.Array,
                           val: jax.Array, valid: jax.Array, d: int,
                           group: int = 4, hi: int = 128,
                           block: int = 2048,
                           compute_dtype=jnp.bfloat16,
                           nnz: int | None = None,
                           interpret: bool | None = None) -> jax.Array:
    """(k, d+1) stats matrix straight from padded-ELL rows.

    The sparse-path answer to the densify bound (doc/benchmarks.md "ELL
    densify bound"): instead of scatter-densifying on the VPU
    (~2·nnz·d lane-ops per row), the kernel splits each feature index
    into (hi, lo) digits and reconstructs G-row groups of dense rows
    with ONE well-tiled MXU matmul per group batch — nnz·(hi + G·lo)
    VPU compare-ops and G·nnz·d MXU MACs per row — then finishes the
    whole k-means stats pass in VMEM.  ``d`` must be divisible by
    ``hi`` (the caller pads features); rows must divide into ``block``.

    ``idx``/``val`` are flat (n, nnz) ELL arrays (pad index ``d``, pad
    value 0), or — when ``nnz`` is passed explicitly — PRE-GROUPED
    (n/G, G·nnz) arrays.  Callers staging big shards must group on the
    host and ship the grouped layout: a device array with a 32-wide
    minor dimension is lane-padded to 128 (4x the memory — a flat
    50M x 32 int32 staging OOMed 16 GB HBM), while (n/G, G·nnz) with
    G·nnz = 128 tiles exactly.  Returns counts in the last column like
    :func:`kmeans_stats_fused`.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k, dc = centroids.shape
    if dc != d:
        raise ValueError(f"centroids dim {dc} != d {d}")
    if nnz is None:
        n, nnz = idx.shape
        idx = idx.reshape(n // group, group * nnz)
        val = val.reshape(n // group, group * nnz)
    else:
        if idx.shape[1] != group * nnz:
            raise ValueError(f"grouped idx width {idx.shape[1]} != "
                             f"group*nnz = {group * nnz}")
        n = idx.shape[0] * group
    lo = d // hi
    if lo * hi != d:
        raise ValueError(f"d={d} not divisible by hi={hi}")
    if nnz & (nnz - 1) or hi & (hi - 1):
        raise ValueError(f"nnz={nnz} and hi={hi} must be powers of two "
                         "(the kernel splits indices with shifts)")
    if n % block or block % group:
        raise ValueError(f"n={n} must divide into block={block} "
                         f"(multiple of group={group})")
    kp = _round_up(k, 8)

    cnorm = centroids.astype(jnp.float32)
    cnorm = cnorm / (jnp.linalg.norm(cnorm, axis=1, keepdims=True) + 1e-12)
    cnorm = jnp.pad(cnorm.astype(jnp.dtype(compute_dtype)),
                    ((0, kp - k), (0, 0)))

    sums, counts = _ell_stats_call(
        cnorm, idx, val.astype(jnp.float32), valid.astype(jnp.float32),
        d, group, hi, block, interpret, k, jnp.dtype(compute_dtype).name)
    return jnp.concatenate([sums[:k], counts[:k]], axis=1)


def kmeans_stats_fused(centroids: jax.Array, x: jax.Array,
                       valid: jax.Array, block: int | None = None,
                       interpret: bool | None = None) -> jax.Array:
    """(k, d+1) stats matrix (counts in the last column) for dense rows.

    ``centroids`` (k, d) are L2-normalised internally (cosine distance,
    reference: kmeans.cc:63-79); ``x`` is (n, d) dense rows with invalid
    rows arbitrary, ``valid`` (n,) 1/0.  The similarity pass runs in
    ``x``'s dtype (bf16 halves the single HBM read); accumulation is
    always float32.  Pads k/d/n to hardware tiles, slices the result
    back.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k, d = centroids.shape
    n = x.shape[0]
    cdt = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
    kp, dp = _round_up(k, 8), _round_up(d, 128)
    if block is None:
        block = default_block(n, d, jnp.dtype(cdt).itemsize)
    block = min(block, _round_up(n, 8))
    npad = _round_up(n, block)

    cnorm = centroids.astype(jnp.float32)
    cnorm = cnorm / (jnp.linalg.norm(cnorm, axis=1, keepdims=True) + 1e-12)
    cnorm = jnp.pad(cnorm.astype(cdt), ((0, kp - k), (0, dp - d)))
    xp = jnp.pad(x.astype(cdt), ((0, npad - n), (0, dp - d)))
    vp = jnp.pad(valid.astype(jnp.float32), (0, npad - n))

    sums, counts = _stats_call(cnorm, xp, vp, block, interpret, k)
    stats = jnp.concatenate([sums[:k, :d], counts[:k]], axis=1)
    return stats
