"""Device-mesh helpers.

The reference builds its communication topology host-side: the tracker
computes a binary tree + ring over worker TCP links
(reference: tracker/rabit_tracker.py:150-198).  On TPU the topology is the
hardware's: chips are wired in an ICI torus and XLA chooses the collective
algorithm.  What we configure instead is the *logical* mesh — which axes of
the device grid carry the data-parallel reduction — so this module is the
TPU-native counterpart of the tracker's topology map.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


DATA_AXIS = "dp"


def make_mesh(
    axis_sizes: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all).

    With no ``axis_sizes``, all devices go onto one data-parallel axis —
    the reference's model, where every worker participates in every
    allreduce (reference: SURVEY.md §2.2 — DP is the core model).
    """
    devs = list(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        axis_sizes = (len(devs),) + (1,) * (len(axis_names) - 1)
    if int(np.prod(axis_sizes)) != len(devs):
        raise ValueError(
            f"mesh axes {tuple(axis_sizes)} do not cover {len(devs)} devices")
    grid = np.array(devs).reshape(axis_sizes)
    return Mesh(grid, tuple(axis_names))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_batch(mesh: Mesh, axis: str = DATA_AXIS, ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch/row) dim over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def local_data_slice(rank: int, world: int, n: int) -> slice:
    """The contiguous row range rank owns under even sharding.

    Mirrors the reference's per-rank data split (reference:
    rabit-learn/utils/data.h:52-55 — per-rank file shards).  Ranges are
    balanced to within one row: the first ``n % world`` ranks get one extra.
    """
    base, extra = divmod(n, world)
    start = rank * base + min(rank, extra)
    return slice(start, start + base + (1 if rank < extra else 0))
