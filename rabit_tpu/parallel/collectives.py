"""In-program collectives: the TPU data plane.

The reference's data plane is a hand-rolled pipelined binary-tree
allreduce over TCP (reference: src/allreduce_base.cc:326-491) and a tree
flood broadcast (reference: src/allreduce_base.cc:500-588).  On TPU these
become XLA collectives over ICI inside ``shard_map``/``jit`` — the
compiler schedules them onto the torus, so the tree/ring scheduling logic
the reference implements by hand disappears into XLA.

Two layers live here:

* thin named-axis wrappers (``allreduce``/``broadcast``/...) for use
  inside ``shard_map`` — these are what model code calls;
* ``ring_allreduce`` — an explicit bandwidth-optimal ring
  (reduce-scatter + all-gather by ``ppermute``), the lax-level blueprint
  for the Pallas kernel in :mod:`rabit_tpu.ops.ring_allreduce` and the
  moral successor of the reference's chunked ring-buffer pipelining
  (reference: src/allreduce_base.h:256-295).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from rabit_tpu.ops import ReduceOp, apply_op_jax, apply_op_pairwise


def allreduce(x: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM):
    """Allreduce along a mesh axis (inside shard_map/jit).

    Lowers MAX/MIN/SUM onto pmax/pmin/psum; PROD and bitwise ops gather +
    reduce (reference op set: include/rabit/rabit-inl.h:55-92).
    """
    return apply_op_jax(op, x, axis_name)


def broadcast(x: jax.Array, axis_name: str, root: int = 0):
    """Any-root broadcast along a mesh axis.

    The reference's tree flood with dynamic root probing
    (reference: src/allreduce_base.cc:500-588) becomes: mask all shards
    but the root's, then psum — XLA lowers this to a broadcast-like
    collective on ICI.
    """
    if isinstance(root, int) and not 0 <= root < lax.axis_size(axis_name):
        raise ValueError(
            f"broadcast: root {root} out of range for axis {axis_name!r} "
            f"of size {lax.axis_size(axis_name)}")
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    if x.dtype == jnp.bool_:
        return lax.psum(masked.astype(jnp.int32), axis_name).astype(x.dtype)
    return lax.psum(masked, axis_name)


def allgather(x: jax.Array, axis_name: str, axis: int = 0, tiled: bool = False):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis_name: str, axis: int = 0):
    """Sum-reduce then scatter shards along ``axis`` (psum_scatter)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ring_allreduce(x: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM,
                   unroll: bool = False):
    """Explicit bandwidth-optimal ring allreduce via ppermute.

    **Correctness blueprint, not the production path**: `lax.psum`
    already lowers to XLA's own pipelined ring/torus collectives on ICI
    (and to one native Gloo allreduce on CPU), so a hand-built
    ppermute ring pays 2(N-1) separate collective dispatches for the
    same wire bytes and cannot beat it (measured 2-4x slower on the
    8-device CPU mesh, doc/benchmarks.md).  It exists to document the
    wire algorithm the reference implements by hand
    (reference: src/allreduce_base.cc:408-455), as the lowering target
    the Pallas credit-flow ring (`ops/ring_allreduce.py`) verifies
    against, and as the fallback shape for ops XLA has no collective
    for (e.g. the PROD/bitwise paths in :func:`allreduce`).

    reduce-scatter phase: N-1 steps, each rank forwards a rotating chunk
    to its ring successor and combines what arrives; all-gather phase:
    N-1 steps circulating the finished chunks.  Total bytes on the wire
    per rank: 2(N-1)/N x payload — the classic ring bound.

    ``unroll=True`` emits the N-1 steps as straight-line code — tried
    for VERDICT r2's hypothesis that the fori_loop back-edge defeats
    overlap; measured on the 8-device CPU mesh it does NOT close the
    gap (the dispatch cost is per-ppermute, not per-loop-iteration), so
    the compact loop stays the default.  The chunk *indices* are
    dynamic either way — they depend on ``axis_index``, which SPMD
    makes a traced value by construction.

    The flat payload is zero-padded to a multiple of N chunks.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    size = flat.shape[0]
    chunk = -(-size // n)  # ceil
    flat = jnp.pad(flat, (0, chunk * n - size))
    chunks = flat.reshape(n, chunk)

    fwd = [(i, (i + 1) % n) for i in range(n)]
    me = lax.axis_index(axis_name)

    def combine(a, b):
        return apply_op_pairwise(op, a, b)

    # reduce-scatter: after step s, rank r holds the partial for chunk
    # (r - s) with contributions from s+1 ranks.
    def rs_step(s, chunks):
        send_idx = (me - s) % n
        payload = lax.dynamic_index_in_dim(chunks, send_idx, keepdims=False)
        recvd = lax.ppermute(payload, axis_name, perm=fwd)
        recv_idx = (me - s - 1) % n
        mine = lax.dynamic_index_in_dim(chunks, recv_idx, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            chunks, combine(mine, recvd), recv_idx, axis=0)

    # all-gather: circulate finished chunks around the ring.
    def ag_step(s, chunks):
        send_idx = (me + 1 - s) % n
        payload = lax.dynamic_index_in_dim(chunks, send_idx, keepdims=False)
        recvd = lax.ppermute(payload, axis_name, perm=fwd)
        recv_idx = (me - s) % n
        return lax.dynamic_update_index_in_dim(chunks, recvd, recv_idx, axis=0)

    if unroll:
        for s in range(n - 1):
            chunks = rs_step(s, chunks)
        for s in range(n - 1):
            chunks = ag_step(s, chunks)
    else:
        chunks = lax.fori_loop(0, n - 1, rs_step, chunks)
        chunks = lax.fori_loop(0, n - 1, ag_step, chunks)
    return chunks.reshape(-1)[:size].reshape(shape).astype(dtype)


def shard_collective(mesh: Mesh, fn: Callable, in_specs, out_specs,
                     check_vma: bool = True):
    """jit(shard_map(fn)) with this mesh — the standard launch wrapper.

    ``check_vma=False`` for bodies containing ``pallas_call`` (its
    outputs carry no varying-across-mesh annotation, so the static
    replication check cannot see through them)."""
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_vma))
