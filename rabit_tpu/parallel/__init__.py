"""Parallelism layer: device meshes and in-program collectives.

TPU-native counterpart of the reference's topology + collective machinery
(tracker tree/ring maps and the socket allreduce loops) — here the
topology is the hardware ICI torus and the collectives are XLA's.
"""
from rabit_tpu.parallel.mesh import (
    DATA_AXIS,
    local_data_slice,
    make_mesh,
    replicated,
    sharded_batch,
)
from rabit_tpu.parallel.collectives import (
    allgather,
    allreduce,
    apply_op_pairwise,
    broadcast,
    reduce_scatter,
    ring_allreduce,
    shard_collective,
)

__all__ = [
    "DATA_AXIS",
    "make_mesh",
    "replicated",
    "sharded_batch",
    "local_data_slice",
    "allreduce",
    "allgather",
    "broadcast",
    "reduce_scatter",
    "ring_allreduce",
    "apply_op_pairwise",
    "shard_collective",
]
