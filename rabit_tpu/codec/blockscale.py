"""Block-scaled int8/int4 wire codecs (EQuARX-style).

Each block of ``block`` f32 elements is quantized symmetrically against
its own absmax — ``scale = absmax / qmax``, ``q = clip(rint(v/scale))``
— and travels as ONE structured wire element::

    int8:  [ f32 scale | block x i1  ]        (~0.27x the f32 bytes)
    int4:  [ f32 scale | block/2 x u1 ]       (~0.14x; two nibbles/byte)

Because a whole encoded block IS one numpy item, every schedule's
item-aligned chunk math (tree chunk windows, ring/halving block bounds,
swing sub-chunks, the hierarchical drain) moves whole blocks by
construction — no schedule needs to know the codec exists.  Hop-path
reductions (the engine's ``_wire_merge`` seam) dequantize both sides,
accumulate in f32, requantize into the destination blocks, and record
the requantization residual at the matching element positions; the
final decode happens once, after the schedule completes.

The merge is **symmetric** (f32 addition is bitwise commutative and
the requantization is a pure function of the accumulated value), so
the exchange-style schedules (swing, halving's paired exchanges) leave
identical bits on both sides of every pairing — cross-rank result
parity holds exactly as it does for the classic wire.

Error feedback (dual-sided, feedback.py): the encode adds the stream's
carried residual to the contribution before quantizing, and the new
residual — encode error plus every hop residual this rank introduced —
commits only when the op completes, so pyrobust retries re-encode
bit-identical wire bytes.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.codec import kernel as kernel_mod
from rabit_tpu.codec.base import Codec
from rabit_tpu.codec.feedback import FeedbackBuffer
from rabit_tpu.ops import ReduceOp


class _OpState:
    """Per-op codec state: the wire array plus the residual ledgers.
    Created at encode, discarded on a failed attempt (transactional —
    nothing commits to the feedback buffer until ``finish``).

    Also owns the fused hop kernel's scratch: the pipelined hop loops
    call :meth:`BlockScaleCodec.merge` once per in-flight chunk, and a
    fresh allocation per call was a measurable slice of the hop math —
    two f32 work panes are leased here instead, grown to the largest
    chunk the op sees and reused for every later merge."""

    __slots__ = ("key", "nelems", "wire", "enc_res", "hop", "_scr")

    def __init__(self, key: tuple, nelems: int, wire: np.ndarray,
                 enc_res: np.ndarray, hop: np.ndarray) -> None:
        self.key = key
        self.nelems = nelems
        self.wire = wire          # structured (nblocks,) block array
        self.enc_res = enc_res    # (nblocks, block) f32 encode residual
        self.hop = hop            # (nblocks, block) f32 hop residuals
        self._scr: np.ndarray | None = None  # fused-merge work panes

    def panes(self, ne: int, block: int) -> tuple[np.ndarray, np.ndarray]:
        """Two (ne, block) f32 scratch panes for one fused merge."""
        need = ne * block
        if self._scr is None or self._scr.size < 2 * need:
            self._scr = np.empty(2 * need, np.float32)
        return (self._scr[:need].reshape(ne, block),
                self._scr[need:2 * need].reshape(ne, block))


class BlockScaleCodec(Codec):
    """Shared int8/int4 machinery; ``bits`` picks the payload width."""

    elementwise = False

    def __init__(self, bits: int, block: int, min_bytes: int,
                 kernel=None) -> None:
        self.bits = int(bits)
        self.block = int(block)
        self.min_bytes = int(min_bytes)
        if self.bits == 8:
            self.name = "int8"
            self.qmax = 127
            qfield = ("q", np.int8, (self.block,))
        else:
            self.name = "int4"
            # [-7, 7]: the -8 code is unused so the range stays
            # symmetric (an asymmetric quantizer would bias the sum —
            # exactly what error feedback must not have to fight).
            self.qmax = 7
            qfield = ("q", np.uint8, (self.block // 2,))
        #: one wire element = one encoded block (scale + payload); the
        #: schedules' item-aligned chunking therefore never splits a
        #: block across a chunk or a ring/halving partition boundary
        self.block_dtype = np.dtype([("s", np.float32), qfield])
        self._bind_kernel(kernel)

    def _bind_kernel(self, kernel) -> None:
        """Attach a compiled-kernel handle (codec/kernel.py) or None
        for the numpy reference.  Implementation choice ONLY: the two
        paths are contractually bit-identical (the C side mirrors the
        numpy ufunc semantics op for op), so a mixed-impl world, replay
        after a crash and every schedule's cross-rank parity all hold
        regardless of which side of the seam each rank runs."""
        self._k = kernel
        self._fmt = kernel_mod.FMT[self.name] if kernel is not None else -1

    # ------------------------------------------------------- interface
    def eligible(self, dtype, op: ReduceOp, nbytes: int) -> bool:
        # SUM-only, f32-only (like the bf16 wire), with a size floor:
        # quantization is a bandwidth-regime tool, and tiny control
        # payloads (consensus-style votes, scalar reductions) both gain
        # nothing and deserve exact bits.
        return (op == ReduceOp.SUM and dtype == np.float32
                and nbytes >= self.min_bytes)

    def wire_nbytes(self, nbytes: int) -> int:
        nelems = nbytes // 4
        nblocks = -(-nelems // self.block) if nelems else 0
        return nblocks * self.block_dtype.itemsize

    # ------------------------------------------------------ quant math
    def _deq(self, blocks: np.ndarray) -> np.ndarray:
        """Dequantize structured blocks -> (nblocks, block) f32.
        Delegates to :meth:`_deq_into` — ONE copy of the unpack math,
        so the decode path and the hop-merge residual math can never
        desynchronize (the ``deq + residual == acc`` bitwise contract
        rests on them producing identical f32 products)."""
        q = blocks["q"]
        out = np.empty(q.shape[:-1] + (self.block,), np.float32)
        if self._k is not None:
            self._k.bs_decode(kernel_mod.p8(blocks), kernel_mod.pf32(out),
                              blocks.size, self.block, self._fmt)
        else:
            self._deq_into(blocks, out)
        return out

    def _deq_into(self, blocks: np.ndarray, out: np.ndarray) -> None:
        """Dequantize structured blocks into the preallocated ``out``
        pane — the same ``scale * q`` f32 products as :meth:`_deq`
        (multiply is bitwise commutative), no allocation on the int8
        hot path."""
        q = blocks["q"]
        if self.bits == 4:
            lo = (q & 0x0F).astype(np.int8) - 8
            hi = (q >> 4).astype(np.int8) - 8
            out[..., 0::2] = lo
            out[..., 1::2] = hi
            np.multiply(out, blocks["s"][..., None], out=out)
            return
        np.multiply(q, blocks["s"][..., None], out=out)

    def _requant_into(self, blocks: np.ndarray, acc: np.ndarray,
                      work: np.ndarray, residual: bool) -> None:
        """Requantize ``acc`` (nblocks, block) into ``blocks`` using
        the ``work`` pane for the integral quantized values.  With
        ``residual`` True, ``acc`` is CONSUMED — rewritten in place
        into ``acc - deq(blocks)``, computed from the exact same f32
        products the next dequantize will produce, so ``deq + residual
        == acc`` bitwise; with False the two residual passes are
        skipped entirely (the non-recording side of a replicated
        pairing pays no ledger math)."""
        # max(max, -min) instead of max(|x|): same value, no |x| temp.
        absmax = np.maximum(acc.max(axis=-1), -acc.min(axis=-1))
        scale = (absmax / np.float32(self.qmax)).astype(np.float32)
        # masked divide, not where(nz, qmax/absmax, 0): the unmasked
        # form still evaluates qmax/0 for all-zero blocks (a warning at
        # best, a FP trap under strict modes).
        inv = np.divide(np.float32(self.qmax), absmax,
                        out=np.zeros_like(absmax, np.float32),
                        where=absmax > 0)
        np.multiply(acc, inv[..., None], out=work)
        np.rint(work, out=work)
        np.clip(work, -self.qmax, self.qmax, out=work)
        blocks["s"] = scale
        if self.bits == 4:
            q8 = work.astype(np.int8)
            blocks["q"] = ((q8[..., 0::2] + 8)
                           | ((q8[..., 1::2] + 8) << 4)).astype(np.uint8)
        else:
            # Direct field assign casts the integral f32 values like
            # astype(int8) would (rint+clip made truncation exact).
            blocks["q"] = work
        if residual:
            # residual in place: work (f32, integral) -> scale*work ->
            # acc - that
            np.multiply(work, scale[..., None], out=work)
            np.subtract(acc, work, out=acc)

    def _enc_into(self, blocks: np.ndarray, acc: np.ndarray) -> np.ndarray:
        """Requantize ``acc`` (nblocks, block) into ``blocks``; returns
        the residual (``acc`` rewritten in place — see
        :meth:`_requant_into`).  Encode path: runs once per op, so the
        work pane is allocated fresh here."""
        self._requant_into(blocks, acc, np.empty_like(acc), True)
        return acc

    # ------------------------------------------------------- op hooks
    def begin(self, flat: np.ndarray, feedback: FeedbackBuffer) -> _OpState:
        """Encode one contribution: carried residual added in, wire
        blocks produced, both residual ledgers opened.  Reads (never
        mutates) the feedback buffer, so a failed attempt retried by
        pyrobust re-encodes the identical wire bytes."""
        n = len(flat)
        nblocks = -(-n // self.block)
        v = np.zeros(nblocks * self.block, np.float32)
        v[:n] = flat
        key = (self.name, n)
        prev = feedback.residual(key)
        if prev is not None:
            v[:n] += prev
        acc = v.reshape(nblocks, self.block)
        wire = np.empty(nblocks, dtype=self.block_dtype)
        if self._k is not None:
            # compiled requantize: acc is rewritten in place into the
            # encode residual, exactly like _enc_into
            self._k.bs_encode(kernel_mod.p8(wire), kernel_mod.pf32(acc),
                              nblocks, self.block, self._fmt)
            enc_res = acc
        else:
            enc_res = self._enc_into(wire, acc)
        return _OpState(key, n, wire, enc_res,
                        np.zeros((nblocks, self.block), np.float32))

    def merge(self, state: _OpState, rflat: np.ndarray, e0: int,
              ne: int, src: np.ndarray, record: bool = True) -> None:
        """Fused single-pass hop kernel: reduce ``ne`` received blocks
        into ``rflat[e0:e0+ne]`` — dequantize both sides into the op's
        reused scratch panes, accumulate in f32, requantize straight
        into the destination blocks — with the residual recorded at the
        same absolute block offsets as ever.  One vectorized pass over
        the chunk, zero allocations after the first chunk on the int8
        hot path (the panes live on the op state; int4's nibble
        unpack/pack still allocates its temporaries), and bit-identical
        to the historical
        three-temporary merge: the f32 products, the accumulate order
        and the requantization math are unchanged, only the staging
        is.  ``record=False`` produces identical merged bytes but
        leaves the ledger alone — AND skips the residual passes
        outright (one side of a replicated-exchange pairing (swing)
        records each quantization event, never both; the other side no
        longer pays for math it throws away)."""
        dst = rflat[e0:e0 + ne]
        if self._k is not None:
            # One compiled pass over the chunk: dequantize both sides,
            # accumulate, requantize, residual straight into the hop
            # ledger at the matching offsets — no scratch panes at all.
            self._k.bs_merge(
                kernel_mod.p8(dst), kernel_mod.p8(src), ne, self.block,
                self._fmt, record,
                kernel_mod.pf32(state.hop[e0:e0 + ne]) if record
                else None)
            return
        acc, work = state.panes(ne, self.block)
        self._deq_into(dst, acc)
        self._deq_into(src[:ne], work)
        np.add(acc, work, out=acc)
        self._requant_into(dst, acc, work, record)
        if record:
            state.hop[e0:e0 + ne] += acc

    def finish(self, state: _OpState, flat: np.ndarray,
               feedback: FeedbackBuffer) -> np.ndarray:
        """Decode the reduced wire blocks into ``flat`` and COMMIT the
        stream residual (encode error + every hop residual this rank
        introduced).  Returns the committed residual (obs feeds its
        norm to the ``codec.feedback.norm`` histogram)."""
        flat[:] = self._deq(state.wire).reshape(-1)[:state.nelems]
        res = (state.enc_res + state.hop).reshape(-1)[:state.nelems]
        feedback.commit(state.key, res)
        return res
