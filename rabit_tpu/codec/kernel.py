"""ctypes seam to the compiled codec kernels (librabit_codec.so).

``rabit_codec_impl`` picks the hop-math implementation behind the ONE
Codec seam:

* ``auto`` (default) — use the compiled kernels when the shared
  library loads, else fall back to the numpy reference with a single
  obs-visible warning (never an ImportError: a toolchain-free box must
  stay green on the numpy path);
* ``native`` — require the kernels; a missing/stale library is a
  loud config error (an explicit request deserves honesty, not a
  silent 10x slowdown);
* ``numpy`` — force the reference path (the A/B baseline).

The choice is IMPLEMENTATION ONLY: both paths are contractually
bit-identical (the C side mirrors numpy's ufunc inner-loop semantics,
see native/src/codec_kernels.c), so it is NOT a collective decision —
ranks may mix implementations freely and replay/retry, sched parity
and cross-rank result parity all hold.  tests/test_native_codec.py
enforces the contract.

Library search order: ``RABIT_CODEC_LIB`` (explicit path), then the
package's ``native/lib/librabit_codec.so`` (built by ``make -C
rabit_tpu/native codec``, best-effort at install time via setup.py).
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

from rabit_tpu.utils.checks import check

#: the ``rabit_codec_impl`` vocabulary
IMPLS = ("auto", "native", "numpy")

#: must match RABIT_CODEC_ABI in native/src/codec_kernels.c
ABI = 1

#: block-format codes shared with the C side (enum in codec_kernels.c)
FMT = {"int8": 0, "int4": 1, "fp8e4m3": 2, "fp8e5m2": 3}

_u8p = ctypes.POINTER(ctypes.c_uint8)
_f32p = ctypes.POINTER(ctypes.c_float)
_u16p = ctypes.POINTER(ctypes.c_uint16)


def p8(a) -> "ctypes._Pointer":
    """Byte pointer to a (contiguous) numpy array's data."""
    return ctypes.cast(a.ctypes.data, _u8p)


def pf32(a) -> "ctypes._Pointer":
    return ctypes.cast(a.ctypes.data, _f32p)


def pu16(a) -> "ctypes._Pointer":
    return ctypes.cast(a.ctypes.data, _u16p)


class CodecKernel:
    """Typed handle over one loaded librabit_codec.so."""

    def __init__(self, lib: ctypes.CDLL, path: str) -> None:
        self.path = path
        lib.rabit_codec_abi.restype = ctypes.c_int
        lib.rabit_codec_abi.argtypes = ()
        lib.rabit_bs_merge.restype = None
        lib.rabit_bs_merge.argtypes = (
            _u8p, _u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, _f32p)
        lib.rabit_bs_encode.restype = None
        lib.rabit_bs_encode.argtypes = (
            _u8p, _f32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32)
        lib.rabit_bs_decode.restype = None
        lib.rabit_bs_decode.argtypes = (
            _u8p, _f32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32)
        lib.rabit_bf16_merge.restype = None
        lib.rabit_bf16_merge.argtypes = (_u16p, _u16p, ctypes.c_int64)
        self._lib = lib

    # thin forwarding wrappers: callers hand raw ctypes pointers (the
    # codec owns the numpy-array -> pointer mapping, one place)
    def bs_merge(self, dst, src, nblocks: int, block: int, fmt: int,
                 record: bool, hop) -> None:
        self._lib.rabit_bs_merge(dst, src, nblocks, block, fmt,
                                 1 if record else 0, hop)

    def bs_encode(self, blocks, acc, nblocks: int, block: int,
                  fmt: int) -> None:
        self._lib.rabit_bs_encode(blocks, acc, nblocks, block, fmt)

    def bs_decode(self, blocks, out, nblocks: int, block: int,
                  fmt: int) -> None:
        self._lib.rabit_bs_decode(blocks, out, nblocks, block, fmt)

    def bf16_merge(self, dst, src, n: int) -> None:
        self._lib.rabit_bf16_merge(dst, src, n)


def _lib_path() -> str:
    override = os.environ.get("RABIT_CODEC_LIB", "").strip()
    if override:
        return override
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "lib", "librabit_codec.so")


_lock = threading.Lock()
_loaded = False
_kernel: Optional[CodecKernel] = None
_load_error: Optional[str] = None
_warned = False


def load() -> Optional[CodecKernel]:
    """Load (once) and return the kernel handle, or None with the
    failure recorded in :func:`load_error`.  Never raises: the caller
    decides whether a missing library is fatal (``native``) or a
    fallback (``auto``)."""
    global _loaded, _kernel, _load_error
    with _lock:
        if _loaded:
            return _kernel
        _loaded = True
        path = _lib_path()
        try:
            lib = ctypes.CDLL(path)
            k = CodecKernel(lib, path)
            abi = lib.rabit_codec_abi()
            if abi != ABI:
                _load_error = ("%s speaks codec ABI %d, this build needs "
                               "%d (rebuild: make -C rabit_tpu/native "
                               "codec)" % (path, abi, ABI))
                return None
            _kernel = k
        except (OSError, AttributeError) as e:
            _load_error = "%s: %s" % (path, e)
        return _kernel


def load_error() -> Optional[str]:
    return _load_error


def resolve_impl(impl_raw, log=None) -> tuple[Optional[CodecKernel], str]:
    """Resolve ``rabit_codec_impl`` into ``(kernel-or-None, label)``.

    The label is what the obs plane surfaces (``native`` / ``numpy`` /
    ``numpy-fallback``) so a silent degrade is visible in one glance
    (rabit_top, /status).  The fallback warning fires ONCE per process,
    not per engine."""
    global _warned
    impl = (str(impl_raw).strip().lower()
            if impl_raw not in (None, "") else "auto")
    check(impl in IMPLS, "rabit_codec_impl must be one of %s, got %r",
          "/".join(IMPLS), impl)
    if impl == "numpy":
        return None, "numpy"
    k = load()
    if k is not None:
        return k, "native"
    check(impl != "native",
          "rabit_codec_impl=native but the codec kernel library did not "
          "load (%s); build it with `make -C rabit_tpu/native codec` or "
          "use rabit_codec_impl=auto", load_error())
    if log is not None and not _warned:
        _warned = True
        log.warning("codec kernels unavailable (%s); falling back to "
                    "the numpy wire path (rabit_codec_impl=auto)",
                    load_error())
    return None, "numpy-fallback"
