"""Error-feedback accumulator for the block-scaled codecs.

Classic EF (the dual-sided EQuARX shape): every quantization event this
rank performs — the initial encode of its own contribution AND every
hop-path requantization of a partial sum — leaves a residual, and the
residual is ADDED BACK to this rank's next contribution on the same
stream.  Because the collective is a SUM, error introduced anywhere
shows up exactly once in the global result, so each rank compensating
the error it itself introduced cancels the bias over time; the
per-op error stays bounded by one quantization step.

Commits are **transactional**: the codec reads the residual at encode
time but commits the updated one only after the op completes.  A
LinkError mid-collective therefore leaves the buffer untouched, and
pyrobust's retry re-encodes bit-identical wire bytes from pristine
inputs — replay and the consensus fingerprints never observe a
half-advanced feedback state.

Streams are keyed by ``(codec, nelems)``: the learn layer's repeated
allreduces (histogram sums, kmeans statistics) re-present the same
shapes every iteration, which is exactly the stream EF compensates.
Distinct logical tensors of identical length share a slot — the
carried residual is a *correction*, never a correctness input, so the
worst case of a shared slot is weaker compensation, not a wrong sum.
The table is bounded (LRU eviction) so a shape-churning workload can
not grow it without bound.
"""
from __future__ import annotations

import collections

import numpy as np


class FeedbackBuffer:
    """Bounded per-stream residual store (one f32 array per stream)."""

    def __init__(self, max_streams: int = 64) -> None:
        self._streams: "collections.OrderedDict[tuple, np.ndarray]" = \
            collections.OrderedDict()
        self._max = max(int(max_streams), 1)

    def residual(self, key: tuple):
        """The carried residual for ``key`` (length-n f32 array), or
        None on a fresh stream.  Read-only by contract: mutate via
        :meth:`commit` so a failed op never half-advances the state."""
        res = self._streams.get(key)
        if res is not None:
            self._streams.move_to_end(key)
        return res

    def commit(self, key: tuple, res: np.ndarray) -> None:
        """Atomically replace the stream's residual (called once per
        COMPLETED op; a retried op re-reads the previous value)."""
        self._streams[key] = res
        self._streams.move_to_end(key)
        while len(self._streams) > self._max:
            self._streams.popitem(last=False)

    def __len__(self) -> int:
        return len(self._streams)
