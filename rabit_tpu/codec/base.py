"""The Codec interface — lossy wire formats as pluggable data.

A :class:`Codec` is the ONE wire-format seam between the engine's
reduction path and the transport frame layer: it decides how an
eligible allreduce payload is represented on every link, independent of
WHICH schedule moves the bytes (tree/ring/halving/swing/hier), of
bucket fusion, of the async pump, of pyrobust replay and of the
transport underneath (tcp/shm, with or without integrity framing).
``rabit_wire_codec`` selects one per job (doc/performance.md
"Quantized wire codecs"); the classic full-width wire stays the
default, and the PR-3 bf16 cast is now simply the first codec
(:class:`Bf16Codec`) instead of a special case.

Two codec shapes exist, distinguished by :attr:`Codec.elementwise`:

* **elementwise** (bf16): the wire array's elements reduce directly
  with ``apply_op_numpy`` in a decoupled ``red_dtype`` — exactly the
  transport/merge-dtype split the schedules already speak.  Composes
  with the fused segmented ring (members cast independently).
* **block-scaled** (int8/int4, blockscale.py): each block of
  ``block`` f32 elements travels as ``f32 scale + quantized payload``
  packed into ONE structured wire element, so every schedule's
  item-aligned chunking moves whole blocks by construction.  Hop-path
  reductions dequantize→accumulate→requantize through the engine's
  ``_wire_merge`` seam, carrying the requantization residual in the
  error-feedback accumulator (feedback.py; EQuARX's dual-sided scheme
  is the reference).

Eligibility is a pure function of replicated inputs (dtype, op,
payload size, the uniform codec config), so every rank agrees whether
an op rides the codec — a collective decision, like schedule choice.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.ops import ReduceOp


class Codec:
    """One lossy wire format; subclasses override the hooks below."""

    #: registry key: the ``rabit_wire_codec`` value, the TuningCache
    #: codec dimension and the ``codec.ops.<name>`` obs counter suffix
    name = "?"

    #: True: wire elements reduce via ``apply_op_numpy`` in
    #: :meth:`red_dtype` (the bf16 shape); False: block-scaled — the
    #: engine routes merges through :meth:`merge` instead.
    elementwise = True

    def eligible(self, dtype, op: ReduceOp, nbytes: int) -> bool:
        """Does this codec apply to the given op?  Must be
        deterministic across ranks (it sees only replicated inputs)."""
        raise NotImplementedError

    def wire_nbytes(self, nbytes: int) -> int:
        """TRUE wire bytes for a logical payload of ``nbytes`` — the
        quantity schedule selection and dispatch-size accounting must
        see (replaces the historical hardcoded ``nbytes //= 2`` bf16
        special case)."""
        raise NotImplementedError


class Bf16Codec(Codec):
    """f32 sum-allreduces travel as bf16: half the bytes on every
    link, accumulation in bf16 too (the PR-3 ``rabit_wire_dtype=bf16``
    path, byte-identical — enable only where ~3 significant digits
    suffice; doc/performance.md has the accuracy bound)."""

    name = "bf16"
    elementwise = True

    def eligible(self, dtype, op: ReduceOp, nbytes: int) -> bool:
        # No size floor: the historical bf16 cast applied at every
        # size, and the wire bytes must stay byte-identical to it.
        return op == ReduceOp.SUM and dtype == np.float32

    def wire_nbytes(self, nbytes: int) -> int:
        return nbytes // 2

    @staticmethod
    def red_dtype():
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)

    def encode(self, flat: np.ndarray):
        """Return the ``(transport_u16_array, reduce_dtype)`` pair.
        Transport rides as uint16 (ml_dtypes arrays don't export a
        buffer); the element merges run in bf16 via views."""
        red = self.red_dtype()
        return flat.reshape(-1).astype(red).view(np.uint16), red

    def decode(self, wire: np.ndarray, red) -> np.ndarray:
        return wire.view(red).astype(np.float32)
