"""Wire-codec parameter resolution (``rabit_wire_codec`` and friends).

One resolver shared by every engine that owns a host wire: it folds
the current knob and the deprecated PR-3 alias into a single Codec
instance (or None for the classic full-width wire) so there is exactly
ONE wire-format seam:

* ``rabit_wire_codec = none | bf16 | int8 | int4 | fp8e4m3 | fp8e5m2``
  — the codec (``fp8`` is accepted as an alias for ``fp8e4m3``).
* ``rabit_wire_dtype = bf16`` — the deprecated alias for
  ``rabit_wire_codec=bf16``; kept working (and byte-identical) but
  documented as deprecated.  An explicit ``rabit_wire_codec`` wins.
* ``rabit_codec_block`` — elements per quantization block for the
  block-scaled codecs (default 64; even, 2..4096).  Collective
  decision: must be uniform across ranks, like ``rabit_bucket_bytes``.
* ``rabit_codec_min_bytes`` — payloads below this ride the classic
  wire exactly (default 4KB; 0 quantizes everything).  Also a
  collective decision.
* ``rabit_codec_impl = auto | native | numpy`` — which IMPLEMENTATION
  runs the block-scale hop math (codec/kernel.py).  NOT a collective
  decision: both paths are bit-identical, so ranks may mix freely;
  the engine resolves it separately and hands the kernel handle in.
"""
from __future__ import annotations

from typing import Optional

from rabit_tpu.codec.base import Bf16Codec, Codec
from rabit_tpu.codec.blockscale import BlockScaleCodec
from rabit_tpu.codec.fp8 import Fp8Codec
from rabit_tpu.utils.checks import check

#: the ``rabit_wire_codec`` vocabulary
CODECS = ("none", "bf16", "int8", "int4", "fp8e4m3", "fp8e5m2")

#: accepted spellings that map onto a canonical CODECS entry
ALIASES = {"fp8": "fp8e4m3"}

DEFAULT_BLOCK = 64
DEFAULT_MIN_BYTES = 4 << 10


def make(name: str, block: int = DEFAULT_BLOCK,
         min_bytes: int = DEFAULT_MIN_BYTES,
         kernel=None) -> Optional[Codec]:
    """Build one codec by name; ``none`` returns None (classic wire).
    ``kernel`` is the compiled-kernel handle (codec/kernel.py) the
    block-scaled codecs run their hop math through, or None for the
    numpy reference — bit-identical either way."""
    name = ALIASES.get(name, name)
    check(name in CODECS, "rabit_wire_codec must be one of %s, got %r",
          "/".join(CODECS), name)
    if name == "none":
        return None
    if name == "bf16":
        return Bf16Codec()
    block = int(block)
    check(2 <= block <= 4096 and block % 2 == 0,
          "rabit_codec_block must be an even integer in [2, 4096], "
          "got %r", block)
    min_bytes = int(min_bytes)
    check(min_bytes >= 0, "rabit_codec_min_bytes must be >= 0")
    if name.startswith("fp8"):
        return Fp8Codec(name, block, min_bytes, kernel=kernel)
    return BlockScaleCodec(8 if name == "int8" else 4, block, min_bytes,
                           kernel=kernel)


def resolve(codec_raw, wire_dtype: str, block_raw, min_bytes: int,
            log=None, kernel=None) -> Optional[Codec]:
    """Resolve the engine's codec from the raw params.

    ``codec_raw``/``block_raw`` arrive unparsed (None when unset);
    ``wire_dtype`` is the already-validated ``rabit_wire_dtype`` value
    ("native" or "bf16").  The alias maps to the bf16 codec only when
    ``rabit_wire_codec`` itself is unset — an explicit codec wins, and
    the conflict is logged rather than silently shadowed."""
    name = (str(codec_raw).strip().lower()
            if codec_raw not in (None, "") else None)
    if name is None:
        name = "bf16" if wire_dtype == "bf16" else "none"
    elif wire_dtype == "bf16" and name != "bf16" and log is not None:
        log.info("rabit_wire_codec=%s overrides the deprecated "
                 "rabit_wire_dtype=bf16 alias", name)
    block = (int(block_raw) if block_raw not in (None, "")
             else DEFAULT_BLOCK)
    return make(name, block=block, min_bytes=min_bytes, kernel=kernel)
