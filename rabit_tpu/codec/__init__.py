"""Quantized wire codecs: the one wire-format seam between the
engine's reduction path and the transport frame layer
(doc/performance.md "Quantized wire codecs")."""
from rabit_tpu.codec.base import Bf16Codec, Codec
from rabit_tpu.codec.blockscale import BlockScaleCodec
from rabit_tpu.codec.factory import (CODECS, DEFAULT_BLOCK,
                                     DEFAULT_MIN_BYTES, make, resolve)
from rabit_tpu.codec.feedback import FeedbackBuffer

__all__ = ["Codec", "Bf16Codec", "BlockScaleCodec", "FeedbackBuffer",
           "CODECS", "DEFAULT_BLOCK", "DEFAULT_MIN_BYTES", "make",
           "resolve"]
