"""Quantized wire codecs: the one wire-format seam between the
engine's reduction path and the transport frame layer
(doc/performance.md "Quantized wire codecs").  The block-scale hop
math runs on either side of the compiled-kernel seam
(``rabit_codec_impl``, codec/kernel.py) — bit-identical by contract."""
from rabit_tpu.codec.base import Bf16Codec, Codec
from rabit_tpu.codec.blockscale import BlockScaleCodec
from rabit_tpu.codec.factory import (ALIASES, CODECS, DEFAULT_BLOCK,
                                     DEFAULT_MIN_BYTES, make, resolve)
from rabit_tpu.codec.feedback import FeedbackBuffer
from rabit_tpu.codec.fp8 import FP8_FORMATS, Fp8Codec
from rabit_tpu.codec.kernel import (IMPLS, CodecKernel, load, load_error,
                                    resolve_impl)

__all__ = ["Codec", "Bf16Codec", "BlockScaleCodec", "Fp8Codec",
           "FeedbackBuffer", "CodecKernel",
           "CODECS", "ALIASES", "FP8_FORMATS", "IMPLS",
           "DEFAULT_BLOCK", "DEFAULT_MIN_BYTES",
           "make", "resolve", "load", "load_error", "resolve_impl"]
