"""Block-scaled fp8 wire codecs (e4m3fn / e5m2).

Same family as the int8/int4 codecs (blockscale.py) — each block of
``block`` f32 elements travels as ``f32 scale + block fp8 codes`` in
ONE structured wire element — but the quantized payload keeps a
floating-point mantissa, so small-magnitude elements inside a block
with one large outlier retain relative precision where a fixed-point
int8 grid flushes them to zero.  The trade is fewer bits of precision
at the top of the block's range (e4m3: 3-bit mantissa vs int8's ~7
significant bits at full scale):

* ``fp8e4m3`` — e4m3fn (bias 7, no inf, max 448): the gradient
  workhorse; ~2 significant digits across ~±4 decades within a block.
* ``fp8e5m2`` — e5m2 (bias 15, IEEE-style, max 57344): wider range,
  one fewer mantissa bit — for heavy-tailed blocks.

Quantization maps the block's absmax to the format's max finite value
(``scale = absmax / fp8_max``), values cast with IEEE round-to-nearest
-even via ml_dtypes (the compiled kernel reproduces the cast bit for
bit — tests/test_native_codec.py checks all 256 codes and the
subnormal/tie boundaries).  Everything else — error feedback, the
fused hop merge, replay bit-identity, per-op opt-out, tuner keying,
honest wire-byte accounting (4 + block bytes per block, ~1.06x over
int8) — is inherited from :class:`BlockScaleCodec` unchanged.
"""
from __future__ import annotations

import numpy as np

from rabit_tpu.codec.blockscale import BlockScaleCodec

#: wire-name -> (ml_dtypes attr, max finite value)
FP8_FORMATS = {
    "fp8e4m3": ("float8_e4m3fn", 448.0),
    "fp8e5m2": ("float8_e5m2", 57344.0),
}


class Fp8Codec(BlockScaleCodec):
    """Block-scaled fp8; ``fmt`` is ``fp8e4m3`` or ``fp8e5m2``."""

    def __init__(self, fmt: str, block: int, min_bytes: int,
                 kernel=None) -> None:
        mlname, qmax = FP8_FORMATS[fmt]
        # Skip BlockScaleCodec.__init__ (it derives int8/int4 fields
        # from ``bits``); set the shared attributes directly.
        self.bits = 8
        self.block = int(block)
        self.min_bytes = int(min_bytes)
        self.name = fmt
        #: float qmax — the clip bound AND the scale anchor: absmax
        #: maps to the format's max finite value, so the cast can
        #: never overflow past the clip
        self.qmax = np.float32(qmax)
        self.block_dtype = np.dtype([("s", np.float32),
                                     ("q", np.uint8, (self.block,))])
        import ml_dtypes

        self._ml = np.dtype(getattr(ml_dtypes, mlname))
        self._bind_kernel(kernel)

    # --------------------------------------------------- numpy path
    def _deq_into(self, blocks: np.ndarray, out: np.ndarray) -> None:
        """fp8 -> f32 (exact) then the same ``value * scale`` f32
        products as the int paths."""
        out[...] = blocks["q"].view(self._ml)
        np.multiply(out, blocks["s"][..., None], out=out)

    def _requant_into(self, blocks: np.ndarray, acc: np.ndarray,
                      work: np.ndarray, residual: bool) -> None:
        """Same skeleton as the int requant, with the rint+clip grid
        snap replaced by clip + an RNE fp8 cast; the residual uses the
        exact f32 products the next dequantize will produce."""
        absmax = np.maximum(acc.max(axis=-1), -acc.min(axis=-1))
        scale = (absmax / self.qmax).astype(np.float32)
        inv = np.divide(self.qmax, absmax,
                        out=np.zeros_like(absmax, np.float32),
                        where=absmax > 0)
        np.multiply(acc, inv[..., None], out=work)
        # Clip BEFORE the cast: absmax maps to qmax exactly, but the
        # rounded ``inv`` can push interior products epsilon past it,
        # and e4m3fn overflows to NaN rather than saturating.
        np.clip(work, -self.qmax, self.qmax, out=work)
        q = work.astype(self._ml)
        blocks["s"] = scale
        blocks["q"] = q.view(np.uint8)
        if residual:
            np.multiply(q.astype(np.float32), scale[..., None], out=work)
            np.subtract(acc, work, out=acc)
