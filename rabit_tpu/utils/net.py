"""Network helpers shared by engines, tracker and launchers."""
from __future__ import annotations

import socket


def routable_ip(target: tuple[str, int] | None = None) -> str:
    """The local interface address peers can reach this process on.

    Loopback targets stay loopback; otherwise the UDP-connect trick picks
    the interface that routes toward ``target`` (no packet is sent).
    ``gethostbyname(gethostname())`` is the last resort — it returns
    127.0.1.1 on stock Debian hosts, which peers cannot reach.
    """
    if target is not None and target[0] in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        probe.connect(target if target is not None else ("8.8.8.8", 80))
        return probe.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        probe.close()
