"""Network helpers shared by engines, tracker and launchers."""
from __future__ import annotations

import socket


def free_port(host: str = "") -> int:
    """A locally-bindable TCP port (bind port 0, read it back, close).

    Inherently racy — another process can claim the port between close
    and the caller's own bind — but the standard trick for handing a
    fixed port to a subprocess that must come up on a KNOWN address
    (e.g. a restartable tracker, jax.distributed's coordinator).  No
    SO_REUSEADDR on the probe: with it the kernel may pick a port held
    by a TIME_WAIT connection, which a consumer that does not set the
    option (the jax coordinator) then cannot bind."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def routable_ip(target: tuple[str, int] | None = None) -> str:
    """The local interface address peers can reach this process on.

    Loopback targets stay loopback; otherwise the UDP-connect trick picks
    the interface that routes toward ``target`` (no packet is sent).
    ``gethostbyname(gethostname())`` is the last resort — it returns
    127.0.1.1 on stock Debian hosts, which peers cannot reach.
    """
    if target is not None and target[0] in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        probe.connect(target if target is not None else ("8.8.8.8", 80))
        return probe.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        probe.close()
