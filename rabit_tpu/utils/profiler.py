"""Timing / tracing helpers.

The reference's tracing story is a wall-clock helper plus per-version
stats from the mock engine (reference: include/rabit/timer.h:48-56,
src/allreduce_mock.h:44-96).  The TPU-native additions: a ``Timer``
accumulator with the same mean/std aggregation speed_test uses, and
``trace`` — a context manager around ``jax.profiler`` that captures a
device trace (XLA op timeline, ICI collectives) viewable in
TensorBoard/Perfetto, the idiomatic way to profile the device data
plane.
"""
from __future__ import annotations

import contextlib
import time


def get_time() -> float:
    """Seconds on a monotonic clock (reference: utils::GetTime)."""
    return time.perf_counter()


class Timer:
    """Accumulate wall-time over repeated sections."""

    def __init__(self) -> None:
        self.total = 0.0
        self.count = 0
        self._t0: float | None = None

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        assert self._t0 is not None
        self.total += time.perf_counter() - self._t0
        self.count += 1
        self._t0 = None

    @property
    def mean(self) -> float:
        return self.total / max(self.count, 1)


@contextlib.contextmanager
def trace(logdir: str, host_profiling: bool = True):
    """Capture a JAX device trace under ``logdir``.

    Wraps ``jax.profiler.trace`` when JAX is importable; degrades to a
    no-op otherwise so host-only engines can keep the call sites.
    """
    try:
        import jax.profiler as _prof
    except ImportError:
        yield
        return
    with _prof.trace(logdir, create_perfetto_trace=host_profiling):
        yield
