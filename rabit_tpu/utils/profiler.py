"""Timing / tracing helpers.

The reference's tracing story is a wall-clock helper plus per-version
stats from the mock engine (reference: include/rabit/timer.h:48-56,
src/allreduce_mock.h:44-96).  The TPU-native additions: a ``Timer``
accumulator with mean/std/max aggregation — a thin face over the
telemetry subsystem's log2-bucket histogram
(:class:`rabit_tpu.obs.metrics.Histogram`), so the two share one
Welford implementation — and ``trace``, a context manager around
``jax.profiler`` that captures a device trace (XLA op timeline, ICI
collectives) viewable in TensorBoard/Perfetto, the idiomatic way to
profile the device data plane.
"""
from __future__ import annotations

import contextlib
import time

from rabit_tpu.obs.metrics import Histogram


def get_time() -> float:
    """Seconds on a monotonic clock (reference: utils::GetTime)."""
    return time.perf_counter()


class Timer:
    """Accumulate wall-time over repeated sections.

    ``with timer: ...`` records one section; ``mean``/``std``/``max``
    aggregate over sections (Welford, exact).  The underlying
    :class:`~rabit_tpu.obs.metrics.Histogram` is exposed for percentile
    estimates and obs-style snapshots.
    """

    def __init__(self) -> None:
        self.histogram = Histogram()
        self._t0: float | None = None

    def __enter__(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        assert self._t0 is not None
        self.histogram.observe(time.perf_counter() - self._t0)
        self._t0 = None

    @property
    def total(self) -> float:
        return self.histogram.sum

    @property
    def count(self) -> int:
        return self.histogram.count

    @property
    def mean(self) -> float:
        return self.histogram.mean

    @property
    def std(self) -> float:
        return self.histogram.std

    @property
    def max(self) -> float:
        return self.histogram.max if self.histogram.count else 0.0


@contextlib.contextmanager
def trace(logdir: str, host_profiling: bool = True):
    """Capture a JAX device trace under ``logdir``.

    Wraps ``jax.profiler.trace`` when JAX is importable; degrades to a
    no-op otherwise so host-only engines can keep the call sites.
    """
    try:
        import jax.profiler as _prof
    except ImportError:
        yield
        return
    with _prof.trace(logdir, create_perfetto_trace=host_profiling):
        yield
