"""Byte-size parsing for configuration values.

The reference accepts suffixed sizes for its collective buffer budget
(reference: rabit_reduce_buffer parse, src/allreduce_base.cc:117-132);
this is the shared Python-side parser (the native engine has a C++ twin,
BaseEngine::ParseByteSize).
"""
from __future__ import annotations

_SUFFIXES = {
    "": 1,
    "B": 1,
    "K": 1 << 10,
    "KB": 1 << 10,
    "M": 1 << 20,
    "MB": 1 << 20,
    "G": 1 << 30,
    "GB": 1 << 30,
}


def parse_byte_size(value) -> int:
    """``"256MB"`` / ``"64KB"`` / ``1048576`` -> bytes (int)."""
    import math

    if isinstance(value, (int, float)):
        if not math.isfinite(value):
            raise ValueError(f"byte size must be finite: {value!r}")
        nbytes = int(value)
    else:
        s = str(value).strip().upper()
        idx = len(s)
        while idx > 0 and not (s[idx - 1].isdigit() or s[idx - 1] == "."):
            idx -= 1
        num, suffix = s[:idx], s[idx:].strip()
        if not num or suffix not in _SUFFIXES:
            raise ValueError(
                f"bad byte size {value!r} (want e.g. 256MB, 64KB, 1048576)")
        raw = float(num) * _SUFFIXES[suffix]
        # finite check BEFORE int(): int(inf) raises OverflowError, and
        # callers catch ValueError for bad configuration (the magnitude
        # bound is enforced once below, on nbytes)
        if not math.isfinite(raw):
            raise ValueError(f"byte size out of range: {value!r}")
        nbytes = int(raw)
    if nbytes < 1:
        raise ValueError(f"byte size must be >= 1 byte: {value!r}")
    if nbytes > 9_000_000_000_000_000:  # < 2^53, same bound as the C++ twin
        raise ValueError(f"byte size out of range: {value!r}")
    return nbytes
