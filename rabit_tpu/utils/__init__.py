"""Utility layer: checks, logging, timing, byte streams.

TPU-native rebuild of the reference's L0 portability layer
(reference: include/rabit/utils.h, include/rabit/timer.h,
include/rabit_serializable.h, include/rabit/io.h).
"""
from rabit_tpu.utils.checks import (
    RabitError,
    check,
    assert_,
    error,
    set_error_handler,
    get_time,
    log,
)
from rabit_tpu.utils.serial import (
    Stream,
    MemoryFixSizeBuffer,
    MemoryBufferStream,
    FileStream,
    Serializable,
    PickleSerializable,
    Base64InStream,
    Base64OutStream,
)

__all__ = [
    "RabitError",
    "check",
    "assert_",
    "error",
    "set_error_handler",
    "get_time",
    "log",
    "Stream",
    "MemoryFixSizeBuffer",
    "MemoryBufferStream",
    "FileStream",
    "Serializable",
    "PickleSerializable",
    "Base64InStream",
    "Base64OutStream",
]
