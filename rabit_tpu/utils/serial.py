"""Streams and the serialization contract used by checkpoints.

TPU-native equivalent of the reference's serialization layer
(reference: include/rabit_serializable.h:17-106 IStream/ISerializable;
include/rabit/io.h:29-117 MemoryFixSizeBuffer/MemoryBufferStream;
rabit-learn/utils/base64.h base64 streams for text-safe model transport).

The checkpoint protocol works on *bytes*: a model is anything that can
serialize itself into a stream and restore itself from one.  Python objects
get a default pickle-based implementation (:class:`PickleSerializable`),
matching the reference Python wrapper's pickled checkpoints
(reference: wrapper/rabit.py:232-297).
"""
from __future__ import annotations

import base64
import io
import pickle
import struct
from abc import ABC, abstractmethod
from typing import Any, BinaryIO

from rabit_tpu.utils.checks import check


class Stream(ABC):
    """Minimal byte-stream interface for serialization.

    Reference: include/rabit_serializable.h:17-92 (IStream), including the
    convenience vector/string helpers which here become length-prefixed
    ``write_bytes``/``read_bytes``.
    """

    @abstractmethod
    def read(self, nbytes: int) -> bytes:
        """Read up to ``nbytes``; returns b'' at end of stream."""

    @abstractmethod
    def write(self, data: bytes) -> None:
        """Write all of ``data``."""

    # -- structured helpers (length-prefixed, little-endian) ---------------
    def write_u64(self, value: int) -> None:
        self.write(struct.pack("<Q", value))

    def read_u64(self) -> int:
        raw = self.read(8)
        check(len(raw) == 8, "stream: truncated u64")
        return struct.unpack("<Q", raw)[0]

    def write_bytes(self, data: bytes) -> None:
        self.write_u64(len(data))
        if data:
            self.write(data)

    def read_bytes(self) -> bytes:
        n = self.read_u64()
        data = self.read(n) if n else b""
        check(len(data) == n, "stream: truncated payload (%d != %d)", len(data), n)
        return data

    def write_str(self, s: str) -> None:
        self.write_bytes(s.encode("utf-8"))

    def read_str(self) -> str:
        return self.read_bytes().decode("utf-8")


class MemoryFixSizeBuffer(Stream):
    """Read/write over a fixed, pre-allocated buffer.

    Reference: include/rabit/io.h:29-74.  Backed by a ``memoryview`` so
    writes mutate the caller's buffer in place.
    """

    def __init__(self, buf: bytearray | memoryview):
        self._view = memoryview(buf)
        self._pos = 0

    def read(self, nbytes: int) -> bytes:
        n = min(nbytes, len(self._view) - self._pos)
        out = bytes(self._view[self._pos : self._pos + n])
        self._pos += n
        return out

    def write(self, data: bytes) -> None:
        n = len(data)
        check(self._pos + n <= len(self._view), "MemoryFixSizeBuffer: overflow")
        self._view[self._pos : self._pos + n] = data
        self._pos += n

    def seek(self, pos: int) -> None:
        self._pos = pos

    def tell(self) -> int:
        return self._pos


class MemoryBufferStream(Stream):
    """Growable in-memory stream (reference: include/rabit/io.h:77-117)."""

    def __init__(self, init: bytes = b""):
        self._buf = io.BytesIO(init)

    def read(self, nbytes: int) -> bytes:
        return self._buf.read(nbytes)

    def write(self, data: bytes) -> None:
        self._buf.write(data)

    def seek(self, pos: int) -> None:
        self._buf.seek(pos)

    def getvalue(self) -> bytes:
        return self._buf.getvalue()


class FileStream(Stream):
    """Stream over an open binary file (reference: rabit-learn/utils/io.h)."""

    def __init__(self, fp: BinaryIO):
        self._fp = fp

    def read(self, nbytes: int) -> bytes:
        return self._fp.read(nbytes)

    def write(self, data: bytes) -> None:
        self._fp.write(data)


class Base64InStream(Stream):
    """Read a base64-encoded payload from an underlying text/byte stream.

    Reference: rabit-learn/utils/base64.h (used to pass binary models through
    text-only channels such as Hadoop streaming).  We decode the whole
    underlying payload eagerly — model blobs are small relative to data.
    """

    def __init__(self, fp: BinaryIO):
        raw = fp.read()
        if isinstance(raw, str):
            raw = raw.encode("ascii")
        # Tolerate whitespace/newlines in the encoded payload.
        raw = b"".join(raw.split())
        self._inner = io.BytesIO(base64.b64decode(raw))

    def read(self, nbytes: int) -> bytes:
        return self._inner.read(nbytes)

    def write(self, data: bytes) -> None:  # pragma: no cover - read-only
        raise NotImplementedError("Base64InStream is read-only")


class Base64OutStream(Stream):
    """Write bytes, emitting base64 text to the underlying stream on finish()."""

    def __init__(self, fp: BinaryIO):
        self._fp = fp
        self._pending = io.BytesIO()

    def read(self, nbytes: int) -> bytes:  # pragma: no cover - write-only
        raise NotImplementedError("Base64OutStream is write-only")

    def write(self, data: bytes) -> None:
        self._pending.write(data)

    def finish(self) -> None:
        encoded = base64.b64encode(self._pending.getvalue())
        out = self._fp
        try:
            out.write(encoded)
        except TypeError:
            out.write(encoded.decode("ascii"))


class Serializable(ABC):
    """Checkpointable object contract (reference: include/rabit_serializable.h:95-106)."""

    @abstractmethod
    def save(self, stream: Stream) -> None: ...

    @abstractmethod
    def load(self, stream: Stream) -> None: ...

    def to_bytes(self) -> bytes:
        s = MemoryBufferStream()
        self.save(s)
        return s.getvalue()

    def from_bytes(self, data: bytes) -> None:
        self.load(MemoryBufferStream(data))


class PickleSerializable(Serializable):
    """Wrap an arbitrary Python object as a Serializable via pickle.

    Mirrors the reference Python wrapper, where checkpointed models are
    pickled bytes shipped through the C ABI (reference: wrapper/rabit.py:232-297,
    wrapper/rabit_wrapper.cc:120-155).
    """

    def __init__(self, obj: Any = None):
        self.obj = obj

    def save(self, stream: Stream) -> None:
        stream.write_bytes(pickle.dumps(self.obj))

    def load(self, stream: Stream) -> None:
        self.obj = pickle.loads(stream.read_bytes())


# One-byte format tags so checkpoints round-trip regardless of how the
# model was serialized (custom Serializable, raw bytes, or pickle).
_TAG_PICKLE = b"P"
_TAG_SERIALIZABLE = b"S"
_TAG_BYTES = b"B"


def serialize_model(model: Any) -> bytes:
    """Serialize a checkpoint payload: Serializable, bytes, or picklable."""
    if isinstance(model, Serializable):
        return _TAG_SERIALIZABLE + model.to_bytes()
    if isinstance(model, (bytes, bytearray, memoryview)):
        return _TAG_BYTES + bytes(model)
    return _TAG_PICKLE + pickle.dumps(model)


def deserialize_model(data: bytes, into: Any = None) -> Any:
    """Inverse of :func:`serialize_model`.

    If ``into`` is a Serializable it is restored in place and returned.
    Serializable-format payloads *require* ``into`` (the byte format is
    defined by the model class, mirroring the reference's
    LoadCheckPoint(ISerializable*) contract, include/rabit.h:214-233).
    """
    tag, body = data[:1], data[1:]
    if isinstance(into, Serializable):
        from rabit_tpu.utils.checks import check

        check(tag == _TAG_SERIALIZABLE,
              "load_checkpoint: checkpoint was not saved from a Serializable")
        into.from_bytes(body)
        return into
    if tag == _TAG_BYTES:
        return body
    if tag == _TAG_SERIALIZABLE:
        from rabit_tpu.utils.checks import error

        error("load_checkpoint: model was checkpointed via Serializable; "
              "pass the model instance to restore into")
    return pickle.loads(body)
