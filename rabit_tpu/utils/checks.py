"""Error checking, logging and timing helpers.

TPU-native equivalent of the reference utility layer
(reference: include/rabit/utils.h:100-154 Assert/Check/Error with pluggable
handlers; include/rabit/timer.h:48-56 GetTime).  Unlike the reference, which
exits the process from C, we raise a Python exception by default; the handler
is pluggable so the distributed launchers can turn fatal errors into the
restart-exit-code convention instead.
"""
from __future__ import annotations

import os
import sys
import time
from typing import Callable, NoReturn


class RabitError(RuntimeError):
    """Fatal error raised by the framework's check/assert helpers."""


_error_handler: Callable[[str], None] | None = None


def set_error_handler(handler: Callable[[str], None] | None) -> None:
    """Override what happens on a fatal check failure.

    Mirrors the reference's ``RABIT_CUSTOMIZE_MSG_`` override hooks
    (reference: include/rabit/utils.h:66-84).  ``None`` restores the default
    (raise :class:`RabitError`).
    """
    global _error_handler
    _error_handler = handler


def error(fmt: str, *args) -> NoReturn:
    msg = (fmt % args) if args else fmt
    if _error_handler is not None:
        _error_handler(msg)
    raise RabitError(msg)


def check(cond: bool, fmt: str = "check failed", *args) -> None:
    """User-facing invariant check (reference: include/rabit/utils.h:131-141)."""
    if not cond:
        error(fmt, *args)


def assert_(cond: bool, fmt: str = "assert failed", *args) -> None:
    """Internal invariant check (reference: include/rabit/utils.h:120-129)."""
    if not cond:
        error("AssertError: " + fmt, *args)


def get_time() -> float:
    """Monotonic wall-clock seconds (reference: include/rabit/timer.h:48-56)."""
    return time.monotonic()


def log(fmt: str, *args) -> None:
    """Printf-style logging to stderr, rank-tagged when available."""
    msg = (fmt % args) if args else fmt
    tag = os.environ.get("RABIT_TPU_LOG_TAG", "")
    if tag:
        msg = f"[{tag}] {msg}"
    print(msg, file=sys.stderr, flush=True)
