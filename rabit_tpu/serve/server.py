"""One serving rank: accept → admission → micro-batch → predict → reply.

The process anatomy (doc/serving.md):

* **Data plane** (per-connection reader threads + one batcher thread):
  parse predict requests, run them through the
  :class:`~rabit_tpu.serve.batching.AdmissionGate`, micro-batch against
  the latency budget, answer from the atomically-swapped
  :class:`~rabit_tpu.serve.model.ModelSlot`.  Never touches a
  collective — overload, deadline and shed verdicts are all rank-local
  and typed on the wire.
* **Control plane** (one loop thread, fleet mode only): the rank joins
  the serving world as a tenant job on the multi-tenant tracker
  (pyrobust engine, ``rabit_elastic=1``) and runs one tiny collective
  round per ``rabit_serve_sync_sec``: agree on the newest committed
  model version (allreduce MAX over what each rank's durable store
  advertises), **broadcast** the winning blob from the lowest rank
  holding it so every rank swaps to the SAME version together, then
  commit a checkpoint — the commit boundary where elastic epochs land
  (a SIGKILLed rank's heartbeat EOF scales the world down here; a
  supervisor-spawned joiner is admitted here; a
  ``WorldChangedError`` is caught, logged and the loop continues at
  the new world).  Old version serves until the new one is installed.
* **Health gate**: a rank whose batcher died, whose model never loaded
  or whose listener failed reports failing health (ctrl ``health``)
  and DRAINS: stops accepting, answers queued work with the typed
  DRAINING status, unpublishes its endpoint and exits with
  :data:`EXIT_DRAINED` — the deliberate-leave code the supervisor does
  not restart, and the elastic epoch absorbs the departure.

SLO instruments ride the engine's live telemetry plane
(``serve.requests.*`` counters, ``serve.latency.seconds`` histogram,
``serve.queue_depth`` gauge — doc/observability.md): with
``rabit_obs=1`` and streaming armed they land on the tracker's
``/metrics`` and ``/status`` like every other instrument.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
import time

import numpy as np

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu import obs
from rabit_tpu.serve import dedup as dedup_mod
from rabit_tpu.serve import protocol as SP
from rabit_tpu.serve.batching import AdmissionGate, QueuedRequest
from rabit_tpu.serve.model import ModelError, ModelSlot, ServedModel
from rabit_tpu.tracker import protocol as P
from rabit_tpu.utils.checks import log

#: deliberate drain/leave exit code: the supervisor treats it as "this
#: rank chose to leave the serving world" (scale-down, health gate) and
#: does not spend a restart on it.
EXIT_DRAINED = 43


def parse_qos_budgets(spec: str) -> dict[int, int]:
    """Parse a ``"gold:16,silver:8,bronze:4"`` budget spec into the
    ``{QOS_*: max_queued}`` dict the admission gate takes.  Classes
    left out keep the default (the whole queue)."""
    out: dict[int, int] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        name, _, raw = part.partition(":")
        if name.strip() not in SP.QOS_BY_NAME or not raw.strip():
            raise ValueError(
                f"bad qos budget {part!r} (want e.g. 'bronze:4')")
        out[SP.QOS_BY_NAME[name.strip()]] = int(raw)
    return out


class _Conn:
    """One client connection: socket + a write lock so batcher and
    accept threads never interleave reply frames."""

    __slots__ = ("sock", "wlock", "alive")

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.wlock = threading.Lock()
        self.alive = True

    def send_reply(self, reply: SP.PredictReply) -> bool:
        raw = reply.encode()
        with self.wlock:
            if not self.alive:
                return False
            try:
                self.sock.sendall(raw)
                return True
            except OSError:
                self.alive = False
                return False


class ServeRank:
    """One serving rank (see the module docstring).

    ``distributed=False`` (standalone) runs the full data plane with no
    tracker and no collectives — the unit-test and ``loadgen --once``
    shape; fleet mode is entered by :func:`main` after ``rabit_tpu``
    init."""

    def __init__(self, model_dir: str, *,
                 port: int = 0, host: str = "127.0.0.1",
                 queue_max: int = 256, batch_max: int = 16,
                 batch_wait_ms: float = 5.0,
                 sync_sec: float = 1.0,
                 slow_ms: float = 0.0,
                 endpoints_dir: str | None = None,
                 task_id: str = "serve0",
                 metrics: obs.Metrics | None = None,
                 qos_budgets: dict[int, int] | None = None,
                 dedup_window: int = dedup_mod.DEFAULT_CAPACITY,
                 distributed: bool = False) -> None:
        self.store = ckpt_mod.CheckpointStore(model_dir, rank=0)
        self.slot = ModelSlot()
        self.gate = AdmissionGate(queue_max=queue_max,
                                  batch_max=batch_max,
                                  batch_wait_ms=batch_wait_ms,
                                  qos_budgets=qos_budgets)
        self.dedup = dedup_mod.DedupWindow(dedup_window)
        self.sync_sec = max(float(sync_sec), 0.05)
        #: deliberate PER-REQUEST compute pad (test seam, like
        #: RABIT_SLOW_RANK): fixes this rank's capacity at
        #: ``1000 / slow_ms`` req/s regardless of batch composition —
        #: so the soak/bench's "2x capacity" spike is a fact, not a
        #: box-dependent guess.  Compute scales with rows; batching
        #: amortizes framing and queueing, exactly like a real model.
        self.slow_sec = max(float(slow_ms), 0.0) / 1000.0
        self.endpoints_dir = endpoints_dir
        self.task_id = str(task_id)
        self.distributed = bool(distributed)
        self.metrics = metrics if metrics is not None else obs.Metrics()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()
        self._threads: list[threading.Thread] = []
        self._batcher: threading.Thread | None = None
        self._stop = threading.Event()
        self._drain_requested = threading.Event()
        self._drained = threading.Event()
        self._health_fail: str | None = None
        self._inflight = 0
        self._started = time.time()
        # rank/world as the control loop last saw them (labels only).
        self.rank = 0
        self.world = 1

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self.slot.load_from_store(self.store)
        if self.slot.get() is None:
            log("serve[%s]: no committed model under %s yet; serving "
                "typed errors until one lands", self.task_id,
                self.store.root)
        self._batcher = threading.Thread(target=self._batch_loop,
                                         name="rabit-serve-batch",
                                         daemon=True)
        self._batcher.start()
        t = threading.Thread(target=self._accept_loop,
                             name="rabit-serve-accept", daemon=True)
        t.start()
        self._threads.append(t)
        self._publish_endpoint()
        log("serve[%s]: listening on %s:%d (batch_max=%d wait=%.1fms "
            "queue_max=%d model v%d)", self.task_id, self.host,
            self.port, self.gate.batch_max, self.gate.batch_wait * 1e3,
            self.gate.queue_max, self.slot.version)

    def stop(self) -> None:
        """Tear down without the drain choreography (tests)."""
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self.gate.drain()
        self._unpublish_endpoint()

    # -- endpoint discovery (file-based) -------------------------------
    def _endpoint_path(self) -> str | None:
        if not self.endpoints_dir:
            return None
        return os.path.join(self.endpoints_dir, f"{self.task_id}.json")

    def _publish_endpoint(self) -> None:
        path = self._endpoint_path()
        if path is None:
            return
        doc = {"host": self.host, "port": self.port, "pid": os.getpid(),
               "task_id": self.task_id, "started": self._started}
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.endpoints_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError as e:
            log("serve[%s]: cannot publish endpoint %s: %s",
                self.task_id, path, e)

    def _unpublish_endpoint(self) -> None:
        path = self._endpoint_path()
        if path is None:
            return
        try:
            os.remove(path)
        except OSError:
            pass  # already gone / never published — nothing to undo

    # -- health --------------------------------------------------------
    def health(self) -> str:
        """``"ok"`` or ``"failing: <why>"`` — the supervisor's poll and
        the self-gate both read this.  A missing model is deliberately
        NOT a health failure: a rank started before the first training
        commit serves typed errors until one lands (start() documents
        it) — draining it would destroy a fleet that merely booted
        early, and the error counters already make the state loud."""
        if self._health_fail:
            return f"failing: {self._health_fail}"
        if self._batcher is not None and not self._batcher.is_alive() \
                and not self._stop.is_set():
            return "failing: batcher thread died"
        return "ok"

    def note_health_failure(self, why: str) -> None:
        self._health_fail = str(why)

    # -- stats ---------------------------------------------------------
    def stats(self) -> dict:
        g = self.gate
        return {
            "task_id": self.task_id, "pid": os.getpid(),
            "rank": self.rank, "world": self.world,
            "queue_depth": g.depth(), "inflight": self._inflight,
            "model_version": self.slot.version,
            "model_swaps": self.slot.swaps,
            "admitted": g.stats.admitted,
            "shed_queue_full": g.stats.shed_queue_full,
            "shed_deadline": g.stats.shed_deadline,
            "shed_evicted": g.stats.shed_evicted,
            "timed_out": g.stats.timed_out,
            "per_class": g.stats.per_class,
            "qos_budgets": {SP.QOS_NAMES[q]: b
                            for q, b in g.qos_budgets.items()},
            "dedup": self.dedup.stats(),
            "service_estimate_ms": round(g.service_estimate() * 1e3, 3),
            "draining": g.draining, "health": self.health(),
        }

    def _count(self, status_name: str, qos: int | None = None) -> None:
        self.metrics.counter(f"serve.requests.{status_name}").inc()
        if qos is not None:
            qname = SP.QOS_NAMES.get(qos, "bronze")
            self.metrics.counter(
                f"serve.qos.{qname}.{status_name}").inc()

    def _update_gauges(self) -> None:
        self.metrics.gauge("serve.queue_depth").set(self.gate.depth())
        self.metrics.gauge("serve.inflight").set(self._inflight)
        self.metrics.gauge("serve.model_version").set(self.slot.version)
        # The serving-plane straggler signal: the tracker folds each
        # rank's service-time EWMA against the fleet median into
        # rabit_straggler_score, which the router consumes.
        self.metrics.gauge("serve.svc_ewma_ms").set(
            round(self.gate.service_estimate() * 1e3, 3))

    # -- accept / per-connection readers -------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: shutting down / draining
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._conn_loop,
                                 args=(_Conn(sock),),
                                 name="rabit-serve-conn", daemon=True)
            t.start()

    def _conn_loop(self, conn: _Conn) -> None:
        sock = conn.sock
        try:
            while not self._stop.is_set():
                try:
                    magic = P.recv_u32(sock)
                except (ConnectionError, OSError):
                    return  # client hung up between requests
                if magic == SP.MAGIC_CTRL:
                    self._handle_ctrl(conn)
                    continue
                if magic == SP.MAGIC_PREDICT:
                    req = SP.PredictRequest.recv_tail(sock)
                elif magic == SP.MAGIC_PREDICT2:
                    req = SP.PredictRequest.recv_tail2(sock)
                else:
                    log("serve[%s]: stray client spoke magic 0x%08x; "
                        "dropping the connection", self.task_id, magic)
                    return
                self._handle_predict(conn, req)
        except (SP.ServeProtocolError, P.HandshakeError,
                ConnectionError, OSError) as e:
            log("serve[%s]: connection dropped (%s)", self.task_id, e)
        finally:
            conn.alive = False
            try:
                sock.close()
            except OSError:
                pass

    def _handle_ctrl(self, conn: _Conn) -> None:
        cmd = P.recv_str(conn.sock, max_len=P.MAX_HELLO_STR)
        if cmd == SP.CTRL_STATS:
            reply = json.dumps(self.stats(), sort_keys=True)
        elif cmd == SP.CTRL_HEALTH:
            reply = self.health()
        elif cmd == SP.CTRL_DRAIN:
            reply = "ok"
        else:
            reply = f"unknown ctrl command {cmd!r}"
        # Under the connection's write lock: the protocol allows
        # predict and ctrl frames to share a connection, and a ctrl
        # reply interleaving with a batcher-thread predict reply would
        # desync the client's byte stream.
        with conn.wlock:
            P.send_str(conn.sock, reply)
        if cmd == SP.CTRL_DRAIN:
            self.request_drain("ctrl drain command")

    def _claim_idem(self, conn: _Conn, req: SP.PredictRequest) -> bool:
        """Duplicate suppression at admission.  True = the caller owns
        the serve; False = this copy lost the first-to-commit race and
        was answered with the typed Duplicate reply — carrying the
        winner's cached answer when it already committed, so a retry
        after a lost reply still gets the verified result."""
        state, cached = self.dedup.claim(req.idem_key)
        if state == dedup_mod.NEW:
            return True
        if cached is not None:
            version, preds = cached
            conn.send_reply(SP.PredictReply(
                SP.STATUS_DUPLICATE, req.req_id, model_version=version,
                reason="duplicate: answered from the idempotency cache",
                predictions=preds))
        else:
            conn.send_reply(SP.PredictReply(
                SP.STATUS_DUPLICATE, req.req_id,
                reason="duplicate: original still in flight"))
        self._count("duplicate", req.qos)
        return False

    def _reply_evicted(self) -> None:
        """Answer eviction victims (lower-class work displaced by a
        higher-class arrival at a full queue) with a typed shed."""
        for victim in self.gate.pop_evicted():
            if victim.idem_key:
                self.dedup.release(victim.idem_key)
            self._reply_simple(victim, SP.STATUS_SHED,
                               "overloaded: evicted by a higher class")
            self._count("shed", victim.qos)

    def _handle_predict(self, conn: _Conn, req: SP.PredictRequest
                        ) -> None:
        now = time.monotonic()
        if self._drain_requested.is_set() or self.gate.draining:
            conn.send_reply(SP.PredictReply(
                SP.STATUS_DRAINING, req.req_id,
                reason="rank is draining; retry another endpoint"))
            self._count("draining", req.qos)
            return
        if req.idem_key and not self._claim_idem(conn, req):
            return  # duplicate — answered from the window
        deadline = (now + req.deadline_ms / 1000.0
                    if req.deadline_ms else None)
        qreq = QueuedRequest(
            req_id=req.req_id, features=req.features,
            arrival=now, deadline=deadline, conn=conn,
            qos=req.qos, idem_key=req.idem_key)
        verdict, retry_ms = self.gate.submit(qreq)
        self._reply_evicted()
        if verdict == "admitted":
            self._update_gauges()
            return  # the batcher owns the reply now
        if qreq.idem_key:
            # The claim never reached a serve: release it so the
            # client's retry of this key is not told Duplicate.
            self.dedup.release(qreq.idem_key)
        if verdict == "draining":
            # Raced the drain choreography: same typed answer the
            # queued work got.
            conn.send_reply(SP.PredictReply(
                SP.STATUS_DRAINING, req.req_id,
                reason="rank is draining; retry another endpoint"))
            self._count("draining", req.qos)
            return
        # Typed Overloaded reply — the whole point: answer FAST with a
        # retry hint instead of queueing into a blown deadline.
        reason = ("queue full" if verdict == "shed_queue_full"
                  else "deadline smaller than the queue-wait estimate")
        conn.send_reply(SP.PredictReply(
            SP.STATUS_SHED, req.req_id, retry_after_ms=retry_ms,
            reason=f"overloaded: {reason}"))
        self._count("shed", req.qos)
        self.metrics.counter(f"serve.{verdict}").inc()
        self._update_gauges()

    # -- the batcher ---------------------------------------------------
    def _batch_loop(self) -> None:
        try:
            while not self._stop.is_set():
                batch, expired = self.gate.take_batch()
                for req in expired:
                    # Shed-before-compute: the deadline died in queue.
                    if req.idem_key:
                        self.dedup.release(req.idem_key)
                    self._reply_simple(req, SP.STATUS_TIMEOUT,
                                       "deadline expired in queue")
                    self._count("timeout", req.qos)
                if not batch:
                    if self._drain_requested.is_set():
                        return
                    continue
                self._run_batch(batch)
                self._update_gauges()
        except Exception as e:  # noqa: BLE001 — health gate must see it
            log("serve[%s]: batcher thread failed: %s: %s",
                self.task_id, type(e).__name__, e)
            self.note_health_failure(f"batcher: {e}")
            raise

    def _reply_simple(self, req: QueuedRequest, status: int,
                      reason: str) -> None:
        conn = req.conn
        if conn is not None:
            conn.send_reply(SP.PredictReply(status, req.req_id,
                                            reason=reason))

    def _run_batch(self, batch: list[QueuedRequest]) -> None:
        t0 = time.perf_counter()
        self._inflight = len(batch)
        model = self.slot.get()
        if model is None:
            for req in batch:
                if req.idem_key:
                    self.dedup.release(req.idem_key)
                self._reply_simple(req, SP.STATUS_ERROR,
                                   "no committed model loaded yet")
                self._count("error", req.qos)
            self._inflight = 0
            return
        # Ragged feature lengths: group by dim so one malformed client
        # cannot error a whole batch of well-formed co-batched rows.
        by_dim: dict[int, list[QueuedRequest]] = {}
        for req in batch:
            by_dim.setdefault(len(req.features), []).append(req)
        if self.slow_sec:
            time.sleep(self.slow_sec * len(batch))
        for dim, reqs in by_dim.items():
            if dim != model.dim:
                for req in reqs:
                    if req.idem_key:
                        self.dedup.release(req.idem_key)
                    self._reply_simple(
                        req, SP.STATUS_ERROR,
                        f"feature count {dim} != model dim {model.dim}")
                    self._count("error", req.qos)
                continue
            x = np.stack([r.features for r in reqs])
            preds = model.predict(x)
            now = time.monotonic()
            for i, req in enumerate(reqs):
                if req.idem_key:
                    # Commit BEFORE the reply write: if the reply is
                    # lost, the client's retry of this key gets the
                    # cached answer instead of a second serve.
                    self.dedup.commit(req.idem_key, model.version,
                                      preds[i:i + 1])
                ok = req.conn.send_reply(SP.PredictReply(
                    SP.STATUS_OK, req.req_id,
                    model_version=model.version,
                    predictions=preds[i:i + 1]))
                self._count("ok" if ok else "error", req.qos)
                if ok:
                    self.metrics.histogram(
                        "serve.latency.seconds").observe(
                        now - req.arrival)
        self._inflight = 0
        dt = time.perf_counter() - t0
        self.gate.note_batch(dt)
        self.metrics.counter("serve.batches").inc()
        self.metrics.histogram("serve.batch.size").observe(len(batch))
        self.metrics.histogram("serve.batch.seconds").observe(dt)

    # -- model refresh (standalone face; fleet uses the control loop) --
    def newest_loadable_version(self) -> int:
        """The version this rank should ADVERTISE in the fleet's
        agreement round: the newest store version that actually
        validates, falling back past torn/invalid candidates — a
        trainer killed mid-persist must not wedge the whole fleet's
        agreement on a version nobody can serve.  Never below the
        version already serving; the probe only reads blobs while a
        newer-than-serving version exists un-installed."""
        best = self.slot.version
        for v in self.store.versions():
            if v <= best:
                break
            if self.store.load_version(v) is not None:
                return v
        return best

    def refresh_model(self) -> bool:
        """Poll the durable store and atomically swap a newer committed
        version in (the old one serves until the new one is loaded)."""
        return self.slot.load_from_store(self.store)

    # -- drain ---------------------------------------------------------
    def request_drain(self, why: str) -> None:
        """Begin the leave choreography: unpublish, stop accepting,
        answer everything still queued with the typed DRAINING status.
        Idempotent; the control loop (or :func:`main`) notices
        ``drained`` and exits the process with EXIT_DRAINED."""
        if self._drain_requested.is_set():
            return
        log("serve[%s]: draining (%s)", self.task_id, why)
        self._drain_requested.set()
        self._unpublish_endpoint()
        try:
            self._listener.close()
        except OSError:
            pass
        for req in self.gate.drain():
            if req.idem_key:
                self.dedup.release(req.idem_key)
            self._reply_simple(req, SP.STATUS_DRAINING,
                               f"rank draining: {why}")
            self._count("draining", req.qos)
        self._drained.set()
        self._flight_persist(why)

    def _flight_persist(self, why: str) -> None:
        """Best-effort: persist the collective engine's flight record
        so a postmortem of a drained/killed serving rank names the op
        that was in flight (doc/observability.md "Causal tracing &
        postmortem").  Fleet mode only — solo ranks never init'd an
        engine; no trace dir configured means persist() is a no-op."""
        try:
            from rabit_tpu import engine as engine_mod

            eng = engine_mod.get_engine()
            persist = getattr(eng, "flight_persist", None)
            if persist is not None:
                persist(f"serve_drain: {why}")
        except (RuntimeError, ImportError, OSError) as e:
            log("serve[%s]: flight persist skipped: %s", self.task_id, e)

    @property
    def drained(self) -> bool:
        return self._drained.is_set()


# ---------------------------------------------------------------- fleet
def _control_loop(server: ServeRank, stop: threading.Event) -> None:
    """The fleet-mode control plane (one thread; the ONLY thread that
    touches collectives).  Each round: version agreement + blob
    broadcast + checkpoint commit (the elastic boundary); see the
    module docstring."""
    import rabit_tpu

    eng_version_gauge = server.metrics.gauge("serve.model_version")
    while not stop.wait(server.sync_sec):
        if server.drained:
            return
        try:
            _sync_round(server)
        except rabit_tpu.WorldChangedError as e:
            # An elastic epoch landed at our commit boundary: a rank
            # died (scale-down) or a joiner was admitted (scale-up).
            # Serving state is the model slot — nothing to re-shard;
            # honor the reload contract, adopt the new coordinates and
            # keep answering (traffic never stopped flowing).
            rabit_tpu.load_checkpoint()
            server.rank = rabit_tpu.get_rank()
            server.world = rabit_tpu.get_world_size()
            log("serve[%s]: elastic epoch %d adopted — world %d -> %d, "
                "now rank %d", server.task_id, e.epoch, e.old_world,
                e.new_world, server.rank)
            server.metrics.counter("serve.elastic_epochs").inc()
        except rabit_tpu.RabitError as e:
            # The control plane degraded (tracker restarting, peer
            # recovery in flight).  Serving continues on the current
            # model; the next round retries.
            log("serve[%s]: control round failed (%s: %s); serving "
                "continues on v%d", server.task_id, type(e).__name__,
                e, server.slot.version)
            server.metrics.counter("serve.sync_errors").inc()
        eng_version_gauge.set(server.slot.version)


def _sync_round(server: ServeRank) -> None:
    """One agreement round (collectives in program order)."""
    import rabit_tpu

    best_local = server.newest_loadable_version()
    agree = np.array([best_local], dtype=np.float64)
    rabit_tpu.allreduce(agree, rabit_tpu.MAX)
    target = int(agree[0])
    if target > server.slot.version:
        # Who can serve the blob?  Lowest rank holding a valid copy.
        dc = server.store.load_version(target)
        have = dc is not None
        root = np.array([server.rank if have
                         else rabit_tpu.get_world_size()],
                        dtype=np.float64)
        rabit_tpu.allreduce(root, rabit_tpu.MIN)
        root_rank = int(root[0])
        if root_rank < rabit_tpu.get_world_size():
            raw = rabit_tpu.broadcast(
                dc.raw if have and server.rank == root_rank else None,
                root_rank)
            try:
                server.slot.install(ServedModel.from_disk_checkpoint(
                    ckpt_mod.unpack_blob(raw)))
                server.metrics.counter("serve.model_broadcasts").inc()
            except (ValueError, ModelError) as e:
                log("serve[%s]: broadcast blob for v%d unusable: %s",
                    server.task_id, target, e)
    # The commit boundary: elastic epochs (scale up/down, rank death
    # absorption) land exactly here, never mid-collective.
    rabit_tpu.checkpoint({"v": server.slot.version})
    server.rank = rabit_tpu.get_rank()
    server.world = rabit_tpu.get_world_size()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="one rabit_tpu serving rank (doc/serving.md)")
    ap.add_argument("--model-dir", required=True,
                    help="durable checkpoint store holding the "
                         "committed model versions")
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("RABIT_SERVE_PORT", 0)))
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--endpoints-dir",
                    default=os.environ.get("RABIT_SERVE_ENDPOINTS_DIR"))
    ap.add_argument("--batch-max", type=int,
                    default=int(os.environ.get("RABIT_SERVE_BATCH_MAX",
                                               16)))
    ap.add_argument("--batch-wait-ms", type=float,
                    default=float(os.environ.get(
                        "RABIT_SERVE_BATCH_WAIT_MS", 5)))
    ap.add_argument("--queue-max", type=int,
                    default=int(os.environ.get("RABIT_SERVE_QUEUE_MAX",
                                               256)))
    ap.add_argument("--sync-sec", type=float,
                    default=float(os.environ.get("RABIT_SERVE_SYNC_SEC",
                                                 1.0)))
    ap.add_argument("--slow-ms", type=float,
                    default=float(os.environ.get("RABIT_SERVE_SLOW_MS",
                                                 0.0)),
                    help="deliberate PER-REQUEST compute pad (test "
                         "seam: fixes capacity at 1000/slow_ms req/s "
                         "per rank regardless of batch composition)")
    ap.add_argument("--qos-budgets",
                    default=os.environ.get("RABIT_SERVE_QOS_BUDGETS",
                                           ""),
                    help="per-class admission budgets, e.g. "
                         "'gold:16,silver:8,bronze:4'; an absent "
                         "class may fill the whole queue")
    ap.add_argument("--dedup-window", type=int,
                    default=int(os.environ.get(
                        "RABIT_SERVE_DEDUP_WINDOW",
                        dedup_mod.DEFAULT_CAPACITY)),
                    help="idempotency-cache capacity (keys) for "
                         "hedged-retry duplicate suppression")
    ap.add_argument("--standalone", action="store_true",
                    help="no tracker, no collectives: serve the local "
                         "store only (tests, loadgen --once)")
    args = ap.parse_args(argv)

    task_id = os.environ.get("RABIT_TASK_ID", "serve0")
    metrics = None
    stop = threading.Event()
    if not args.standalone:
        import rabit_tpu
        from rabit_tpu import engine as engine_mod

        rabit_tpu.init()
        rabit_tpu.load_checkpoint()  # align with the job's version
        metrics = engine_mod.get_engine().metrics()

    server = ServeRank(
        args.model_dir, port=args.port, host=args.host,
        queue_max=args.queue_max, batch_max=args.batch_max,
        batch_wait_ms=args.batch_wait_ms, sync_sec=args.sync_sec,
        slow_ms=args.slow_ms, endpoints_dir=args.endpoints_dir,
        task_id=task_id, metrics=metrics,
        qos_budgets=parse_qos_budgets(args.qos_budgets),
        dedup_window=args.dedup_window,
        distributed=not args.standalone)
    if not args.standalone:
        import rabit_tpu

        server.rank = rabit_tpu.get_rank()
        server.world = rabit_tpu.get_world_size()
    server.start()

    def _on_term(_sig, _frm):
        server.request_drain("SIGTERM")
    signal.signal(signal.SIGTERM, _on_term)

    ctl: threading.Thread | None = None
    if not args.standalone:
        ctl = threading.Thread(target=_control_loop,
                               args=(server, stop),
                               name="rabit-serve-ctl", daemon=True)
        ctl.start()

    # Main thread: the health self-gate + standalone model refresh.
    try:
        while not server.drained:
            time.sleep(0.25)
            if args.standalone:
                server.refresh_model()
            verdict = server.health()
            if verdict != "ok":
                server.request_drain(verdict)
    except KeyboardInterrupt:
        server.request_drain("SIGINT")
    stop.set()
    # Deliberate leave WITHOUT the clean rabit goodbye: the heartbeat
    # EOF is the death signal the tracker's elastic scale-down keys on
    # (doc/serving.md "Draining and scale-down") — a clean finalize
    # would instead leave the surviving world waiting on our goodbye.
    log("serve[%s]: drained; leaving the serving world", task_id)
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(EXIT_DRAINED)


if __name__ == "__main__":
    sys.exit(main())
