"""rabit_tpu.serve — the production serving plane (doc/serving.md).

A high-QPS prediction service composed from the existing layers: each
serving rank loads the committed model from the durable checkpoint
store (rabit_tpu/ckpt), registers as a tenant job on the multi-tenant
tracker (rabit_tpu/tracker), answers predict requests over a
length-prefixed TCP protocol, and treats **overload as a first-class,
typed failure mode** — bounded admission with load shedding, per-
request deadline budgets propagated through micro-batch formation,
health-gated draining and queue-depth-driven elastic autoscaling.

* :mod:`rabit_tpu.serve.protocol` — the predict/reply wire frames and
  the typed non-OK statuses (Overloaded/Timeout/Draining);
* :mod:`rabit_tpu.serve.model` — committed blobs → deterministic
  batched predict, atomic version swap (:class:`ModelSlot`);
* :mod:`rabit_tpu.serve.batching` — bounded admission gate (with
  per-QoS-class budgets and lower-class eviction), the deterministic
  shed policy and the latency-budget micro-batcher;
* :mod:`rabit_tpu.serve.dedup` — the bounded idempotency cache behind
  hedged-retry duplicate suppression (typed Duplicate replies);
* :mod:`rabit_tpu.serve.server` — the serving rank (data plane
  threads + the fleet control loop with version-agreement broadcasts
  at checkpoint-commit boundaries).

Drive a fleet with ``python -m rabit_tpu.tools.serve`` and load it
with ``python -m rabit_tpu.tools.loadgen`` (open-loop, verifying).
"""
from rabit_tpu.serve.batching import (AdmissionGate, GateStats,
                                      QueuedRequest)
from rabit_tpu.serve.dedup import DedupWindow
from rabit_tpu.serve.model import (ModelError, ModelSlot, ServedModel,
                                   predict_row)
from rabit_tpu.serve.protocol import (MAGIC_CTRL, MAGIC_PREDICT,
                                      MAGIC_PREDICT2, QOS_BRONZE,
                                      QOS_GOLD, QOS_SILVER,
                                      STATUS_DRAINING, STATUS_DUPLICATE,
                                      STATUS_ERROR,
                                      STATUS_OK, STATUS_SHED,
                                      STATUS_TIMEOUT, PredictReply,
                                      PredictRequest, send_ctrl)
from rabit_tpu.serve.server import (EXIT_DRAINED, ServeRank,
                                    parse_qos_budgets)

__all__ = [
    "AdmissionGate", "GateStats", "QueuedRequest", "DedupWindow",
    "ModelError", "ModelSlot", "ServedModel", "predict_row",
    "MAGIC_CTRL", "MAGIC_PREDICT", "MAGIC_PREDICT2",
    "QOS_BRONZE", "QOS_GOLD", "QOS_SILVER",
    "STATUS_DRAINING", "STATUS_DUPLICATE", "STATUS_ERROR",
    "STATUS_OK", "STATUS_SHED", "STATUS_TIMEOUT", "PredictReply",
    "PredictRequest", "send_ctrl",
    "EXIT_DRAINED", "ServeRank", "parse_qos_budgets",
]
