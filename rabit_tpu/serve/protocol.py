"""Wire protocol of the prediction service (doc/serving.md).

Length-prefixed little-endian frames in the tracker-protocol idiom
(tracker/protocol.py): u32 primitives, u32-length-prefixed strings, no
JSON on the hot path.  One persistent TCP connection carries any number
of request/reply pairs; replies come back in **completion** order (the
micro-batcher may reorder across requests of one connection), matched
to their request by the echoed ``req_id``.

Client → server, per request (v1)::

    u32 MAGIC_PREDICT
    u32 req_id          client-chosen correlation id (echoed verbatim)
    u32 deadline_ms     per-request latency budget measured from server
                        receipt; 0 = no deadline.  Propagated through
                        admission (a request whose queue-wait estimate
                        already exceeds the budget is shed on arrival)
                        and batch formation (an expired request is shed
                        *before* compute — a doomed request never costs
                        model FLOPs).
    u32 nfeat           feature count, then nfeat f32 (the input row)

The v2 frame (ISSUE 20) adds a QoS class and an idempotency key,
**feature-negotiated by magic**: a client that wants neither keeps
emitting the v1 frame above, byte-identical, and a v1 request is
served exactly as before (class silver, no dedup) — old clients and
old servers never see a changed byte::

    u32 MAGIC_PREDICT2
    u32 req_id
    u32 qos             QOS_BRONZE(0) | QOS_SILVER(1) | QOS_GOLD(2) —
                        higher value = higher priority; unknown values
                        clamp to bronze (a stray client cannot buy
                        gold by accident)
    u32 deadline_ms
    u64 idem_key        idempotency key; 0 = none.  Two requests with
                        the same non-zero key are THE SAME logical
                        request (a hedge/retry): the server's bounded
                        dedup window serves at most one and answers
                        the rest with the typed Duplicate status.
    u32 nfeat           feature count, then nfeat f32

Server → client, per request (completion order)::

    u32 status          STATUS_* below
    u32 req_id          echoes the request
    u32 model_version   committed model version that answered (0 for
                        non-OK replies) — the client's bit-consistency
                        check keys on it
    u32 retry_after_ms  for STATUS_SHED: when to retry (the load
                        shedder's drain estimate); 0 otherwise
    str reason          human-readable detail ("" for OK)
    u32 npred           prediction count, then npred f64 (empty unless
                        OK)

A typed non-OK status is the whole point of the overload design
(doc/serving.md "Load shedding"): under overload the service answers
*quickly* with SHED + retry-after instead of queueing until every
deadline is blown — p99 of served requests stays bounded and the
client owns the retry policy.

Control channel, same port (supervisor/ops use, never the data path)::

    u32 MAGIC_CTRL, str cmd       "stats" → str JSON reply
                                  "drain" → str "ok"; the rank stops
                                  accepting, flushes its queue and
                                  leaves the serving world
                                  "health" → str "ok" | "failing: ..."
"""
from __future__ import annotations

import socket
import struct
from dataclasses import dataclass

import numpy as np

from rabit_tpu.tracker.protocol import (recv_all, recv_str, recv_u32,
                                        send_str, send_u32)

MAGIC_PREDICT = 0x7AB15E01
MAGIC_PREDICT2 = 0x7AB15E02
MAGIC_CTRL = 0x7AB15EC1

STATUS_OK = 0
#: admission gate refused the request (queue full / deadline-doomed):
#: retry after ``retry_after_ms`` — the typed Overloaded reply.
STATUS_SHED = 1
#: the deadline budget expired before compute; never predicted.
STATUS_TIMEOUT = 2
#: server-side failure (no model loaded, predict raised).
STATUS_ERROR = 3
#: the rank is draining out of the serving world (health gate /
#: scale-down): retry against another endpoint.
STATUS_DRAINING = 4
#: another copy of the same idempotency key already won (or is in
#: flight): first-to-commit wins, this copy was never served.  If the
#: winner already committed, the reply carries the *cached* committed
#: answer (version + predictions) so a retry after a lost reply still
#: gets the verified result.
STATUS_DUPLICATE = 5

STATUS_NAMES = {STATUS_OK: "ok", STATUS_SHED: "shed",
                STATUS_TIMEOUT: "timeout", STATUS_ERROR: "error",
                STATUS_DRAINING: "draining",
                STATUS_DUPLICATE: "duplicate"}

#: QoS classes, ordered by value: a higher class is admitted first and
#: shed last.  v1 requests (no class on the wire) are silver.
QOS_BRONZE = 0
QOS_SILVER = 1
QOS_GOLD = 2

QOS_NAMES = {QOS_BRONZE: "bronze", QOS_SILVER: "silver",
             QOS_GOLD: "gold"}
QOS_BY_NAME = {v: k for k, v in QOS_NAMES.items()}

#: sanity cap on one request's feature count (a corrupt length prefix
#: must not become an unbounded recv — same discipline as the tracker's
#: handshake caps).
MAX_FEATURES = 1 << 20

CTRL_STATS = "stats"
CTRL_DRAIN = "drain"
CTRL_HEALTH = "health"


class ServeProtocolError(ValueError):
    """A client/server spoke something that is not this protocol."""


@dataclass
class PredictRequest:
    """One predict request as parsed off the wire."""

    req_id: int
    deadline_ms: int
    features: np.ndarray  # f32, 1-D
    #: priority class (v2 frame); v1 requests default to silver.
    qos: int = QOS_SILVER
    #: idempotency key (v2 frame); 0 = no dedup.
    idem_key: int = 0

    @property
    def qos_name(self) -> str:
        return QOS_NAMES.get(self.qos, str(self.qos))

    def encode(self) -> bytes:
        raw = np.ascontiguousarray(self.features,
                                   dtype=np.float32).tobytes()
        if self.qos == QOS_SILVER and self.idem_key == 0:
            # Feature negotiation: a default-class request with no
            # idempotency key stays the v1 frame, byte-identical —
            # old servers keep working and golden-bytes tests hold.
            return struct.pack("<IIII", MAGIC_PREDICT, self.req_id,
                               self.deadline_ms, len(raw) // 4) + raw
        return struct.pack("<IIIIQI", MAGIC_PREDICT2, self.req_id,
                           self.qos, self.deadline_ms, self.idem_key,
                           len(raw) // 4) + raw

    def send(self, sock: socket.socket) -> None:
        sock.sendall(self.encode())

    @classmethod
    def recv_tail(cls, sock: socket.socket) -> "PredictRequest":
        """Parse the v1 frame after the caller consumed the magic."""
        req_id = recv_u32(sock)
        deadline_ms = recv_u32(sock)
        return cls(req_id, deadline_ms, _recv_features(sock))

    @classmethod
    def recv_tail2(cls, sock: socket.socket) -> "PredictRequest":
        """Parse the v2 frame after the caller consumed the magic."""
        req_id = recv_u32(sock)
        qos = recv_u32(sock)
        if qos not in QOS_NAMES:
            # Clamp unknown classes down, never up: a client speaking
            # a future protocol cannot accidentally buy gold here.
            qos = QOS_BRONZE
        deadline_ms = recv_u32(sock)
        idem_key = struct.unpack("<Q", recv_all(sock, 8))[0]
        return cls(req_id, deadline_ms, _recv_features(sock),
                   qos=qos, idem_key=idem_key)


def _recv_features(sock: socket.socket) -> np.ndarray:
    nfeat = recv_u32(sock)
    if nfeat > MAX_FEATURES:
        raise ServeProtocolError(
            f"request feature count {nfeat} exceeds the cap "
            f"{MAX_FEATURES}")
    raw = recv_all(sock, 4 * nfeat)
    return np.frombuffer(raw, dtype="<f4").copy()


@dataclass
class PredictReply:
    """One reply frame (see the module docstring for field semantics)."""

    status: int
    req_id: int
    model_version: int = 0
    retry_after_ms: int = 0
    reason: str = ""
    predictions: np.ndarray | None = None  # f64, 1-D (OK only)

    @property
    def status_name(self) -> str:
        return STATUS_NAMES.get(self.status, str(self.status))

    def encode(self) -> bytes:
        preds = (np.ascontiguousarray(self.predictions,
                                      dtype=np.float64).tobytes()
                 if self.predictions is not None else b"")
        reason = self.reason.encode("utf-8")
        return (struct.pack("<IIII", self.status, self.req_id,
                            self.model_version, self.retry_after_ms)
                + struct.pack("<I", len(reason)) + reason
                + struct.pack("<I", len(preds) // 8) + preds)

    def send(self, sock: socket.socket) -> None:
        sock.sendall(self.encode())

    @classmethod
    def recv(cls, sock: socket.socket) -> "PredictReply":
        status = recv_u32(sock)
        req_id = recv_u32(sock)
        version = recv_u32(sock)
        retry_after = recv_u32(sock)
        reason = recv_str(sock, max_len=4096)
        npred = recv_u32(sock)
        if npred > MAX_FEATURES:
            raise ServeProtocolError(
                f"reply prediction count {npred} exceeds the cap")
        preds = None
        if npred:
            preds = np.frombuffer(recv_all(sock, 8 * npred),
                                  dtype="<f8").copy()
        return cls(status, req_id, version, retry_after, reason, preds)


def send_ctrl(sock: socket.socket, cmd: str) -> str:
    """Issue one control command and return the string reply."""
    send_u32(sock, MAGIC_CTRL)
    send_str(sock, cmd)
    return recv_str(sock, max_len=1 << 20)
