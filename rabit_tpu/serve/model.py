"""The served model: committed checkpoint blobs → deterministic predict.

The serving plane reads the same durable tier the trainers write
(rabit_tpu/ckpt): a model is whatever object the training loop passed to
``rabit_tpu.checkpoint`` — here, the **linear serving convention**: a
dict with a 1-D float64 weight vector under ``"w"`` (the shape
``tools/serve.py``'s trainer and the soak gate's synthesizer both
produce; ``rabit_tpu.learn.linear`` weights slot straight in).

Bit-consistency is a wire contract, not an aspiration: ``predict``
computes each row as ``(x.astype(f64) * w).sum()`` via numpy's pairwise
row reduction, which is **independent of batch composition** — the same
input row yields the same 8 bytes whether it rode a batch of 1 or 64,
so a client can recompute any reply bitwise from the committed blob of
the version the reply names (tools/loadgen.py does exactly that; the
invariant is pinned in tests/test_serve.py).

:class:`ModelSlot` is the atomic-swap holder: the running version
serves every in-flight batch until the *next* version is fully loaded
and validated, then one reference assignment swaps it — a reader never
observes a half-installed model.
"""
from __future__ import annotations

import threading

import numpy as np

from rabit_tpu import ckpt as ckpt_mod
from rabit_tpu.utils.checks import log
from rabit_tpu.utils.serial import deserialize_model


class ModelError(RuntimeError):
    """A blob that does not follow the serving convention."""


class ServedModel:
    """One immutable committed model version (weights + version tag)."""

    def __init__(self, version: int, weights: np.ndarray,
                 raw: bytes = b"") -> None:
        self.version = int(version)
        self.weights = np.ascontiguousarray(weights, dtype=np.float64)
        #: the full CRC-stamped checkpoint blob this model came from —
        #: re-servable as-is over the version broadcast (server.py).
        self.raw = raw

    @property
    def dim(self) -> int:
        return int(self.weights.shape[0])

    @classmethod
    def from_global_blob(cls, version: int, blob: bytes,
                         raw: bytes = b"") -> "ServedModel":
        """Decode one committed ``global`` payload (the bytes
        ``rabit_tpu.checkpoint`` serialized).  Raises
        :class:`ModelError` on anything that is not the serving
        convention — the caller decides whether to fall back or fail
        loudly."""
        try:
            obj = deserialize_model(blob)
        except Exception as e:  # noqa: BLE001 — pickle of foreign bytes
            raise ModelError(f"undecodable model blob: {e}") from e
        if not isinstance(obj, dict) or "w" not in obj:
            raise ModelError(
                "model blob does not follow the serving convention "
                "(need a dict with a 1-D weight vector under 'w')")
        w = np.asarray(obj["w"], dtype=np.float64)
        if w.ndim != 1 or w.size == 0:
            raise ModelError(f"weight vector has shape {w.shape}; "
                             "need a non-empty 1-D vector")
        return cls(version, w, raw=raw)

    @classmethod
    def from_disk_checkpoint(cls, dc: ckpt_mod.DiskCheckpoint
                             ) -> "ServedModel":
        return cls.from_global_blob(dc.version, dc.global_blob,
                                    raw=dc.raw)

    def predict(self, batch: np.ndarray) -> np.ndarray:
        """Batched inference over (B, dim) float32 rows → (B,) float64.

        Row i's value is bitwise independent of the rest of the batch
        (pairwise sum per row — see the module docstring), so replies
        are reproducible from (version, input row) alone."""
        x = np.asarray(batch)
        if x.ndim == 1:
            x = x[None, :]
        if x.shape[1] != self.dim:
            raise ModelError(
                f"feature count {x.shape[1]} != model dim {self.dim}")
        return (x.astype(np.float64) * self.weights).sum(axis=1)


def predict_row(weights: np.ndarray, row: np.ndarray) -> float:
    """Client-side single-row recomputation — BITWISE what the server's
    batched :meth:`ServedModel.predict` produced for this row (the
    loadgen verifier's oracle)."""
    w = np.ascontiguousarray(weights, dtype=np.float64)
    return float((np.asarray(row, dtype=np.float32)
                  .astype(np.float64) * w).sum())


class ModelSlot:
    """Atomic-swap holder of the currently-serving model.

    ``get()`` is one lock-guarded reference read; ``install()`` only
    swaps after the replacement is fully constructed and newer — the
    old version keeps answering until that instant (doc/serving.md
    "Model version rollover")."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._model: ServedModel | None = None
        self.swaps = 0

    def get(self) -> ServedModel | None:
        with self._lock:
            return self._model

    @property
    def version(self) -> int:
        m = self.get()
        return m.version if m is not None else 0

    def install(self, model: ServedModel) -> bool:
        """Swap ``model`` in iff it is strictly newer; returns whether
        the swap happened."""
        with self._lock:
            if self._model is not None \
                    and model.version <= self._model.version:
                return False
            self._model = model
            self.swaps += 1
        log("serve: model version %d installed (dim %d)",
            model.version, model.dim)
        return True

    def load_from_store(self, store: ckpt_mod.CheckpointStore,
                        version: int | None = None) -> bool:
        """Load-and-swap from the durable store: the newest valid
        version (or exactly ``version``).  A blob that fails the
        serving convention falls back older (the store's own CRC
        fallback discipline, extended one layer up); returns whether a
        strictly newer model was installed."""
        if version is not None:
            dc = store.load_version(version)
            candidates = [dc] if dc is not None else []
        else:
            candidates = []
            for v in store.versions():
                if v <= self.version:
                    break  # newest-first: nothing newer remains
                dc = store.load_version(v)
                if dc is not None:
                    candidates.append(dc)
        for dc in candidates:
            try:
                return self.install(ServedModel.from_disk_checkpoint(dc))
            except ModelError as e:
                log("serve: version %d blob unusable (%s); trying older",
                    dc.version, e)
        return False
