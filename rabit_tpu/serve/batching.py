"""Bounded admission, load shedding and deadline-aware micro-batching.

The overload-protection core of the serving plane (doc/serving.md).
Three rules, in order, and nothing else decides who gets served:

1. **Bounded admission** (:class:`AdmissionGate.submit`): the queue
   never exceeds ``queue_max``.  An arrival at a full queue is shed
   with a typed Overloaded reply carrying ``retry_after_ms`` (the
   drain-time estimate) — throughput never comes from unbounded
   queueing, so served-request p99 stays a function of queue depth,
   not of offered load.
2. **Deadline-aware shed-on-arrival**: a request whose own latency
   budget is already smaller than the estimated queue wait is doomed —
   admitting it would burn a batch slot computing an answer the client
   has stopped waiting for.  It is shed immediately instead.
3. **Shed-before-compute** (:meth:`MicroBatcher.take_batch`): a
   request whose deadline expired while queued is dropped at batch
   formation with a typed Timeout reply — expired work never reaches
   the model.

The policy is **deterministic**: verdicts are a pure function of
(queue depth, request deadline, the gate's frozen service-time
estimate) at arrival — replaying the same arrival sequence against the
same gate state replays the same shed set bit-for-bit (pinned in
tests/test_serve.py; the chaos composition leans on it).

The micro-batcher converts queue pressure into batch size: a batch
closes at ``batch_max`` requests or ``batch_wait_ms`` after its first
member, whichever comes first — bounded latency cost under light load,
full batches under heavy load.

**QoS classes** (ISSUE 20) refine rule 1 without changing its shape:
each class (gold/silver/bronze) holds its own admission budget — a
cap on how many of its requests may sit queued at once — so a bronze
flood can never starve gold out of the queue.  When the *total* queue
is full, a higher-class arrival evicts the newest strictly-lower-class
queued request (typed shed to the victim) instead of being refused:
under overload bronze sheds first and gold last, which is the entire
point of having classes.  Defaults keep every class's budget at
``queue_max``, so single-class traffic behaves exactly as before and
the determinism contract is unchanged.
"""
from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from rabit_tpu.serve.protocol import QOS_NAMES, QOS_SILVER


def _class_counts() -> dict:
    return {name: {"offered": 0, "admitted": 0, "shed_queue_full": 0,
                   "shed_deadline": 0, "shed_evicted": 0,
                   "timed_out": 0}
            for name in QOS_NAMES.values()}


@dataclass
class QueuedRequest:
    """One admitted request parked between admission and its batch."""

    req_id: int
    features: np.ndarray
    arrival: float            # monotonic receipt time
    deadline: float | None    # absolute monotonic deadline, None = no
    conn: object = None       # owning connection (reply routing)
    shed: str | None = None   # set when a verdict removed it pre-compute
    qos: int = QOS_SILVER     # priority class (protocol.QOS_*)
    idem_key: int = 0         # idempotency key, 0 = none

    def remaining(self, now: float) -> float:
        return float("inf") if self.deadline is None \
            else self.deadline - now


@dataclass
class GateStats:
    admitted: int = 0
    shed_queue_full: int = 0
    shed_deadline: int = 0
    shed_evicted: int = 0     # bumped by a higher-class arrival
    timed_out: int = 0        # expired in queue, shed at batch formation
    #: per-class sub-books, keyed by QoS name — the per-class
    #: accounting identity (offered == admitted + sheds) checks here.
    per_class: dict = field(default_factory=_class_counts)


class AdmissionGate:
    """Bounded queue + deterministic shed policy + batch formation.

    One gate per serving rank; the accept threads call
    :meth:`submit`, the batcher thread calls :meth:`take_batch`.
    ``service_time_estimate`` is an EWMA of recent per-batch service
    times the batcher feeds back (:meth:`note_batch`) — the basis of
    both the queue-wait estimate and the retry-after hint."""

    def __init__(self, queue_max: int = 256, batch_max: int = 16,
                 batch_wait_ms: float = 5.0,
                 service_time_init_ms: float = 10.0,
                 qos_budgets: dict[int, int] | None = None) -> None:
        self.queue_max = max(int(queue_max), 1)
        self.batch_max = max(int(batch_max), 1)
        self.batch_wait = max(float(batch_wait_ms), 0.0) / 1000.0
        # Per-class admission budgets (qos value -> max queued of that
        # class); an absent class defaults to the whole queue, which
        # makes single-class traffic byte-identical to the pre-QoS
        # gate.
        self.qos_budgets = {q: max(int((qos_budgets or {}).get(
            q, self.queue_max)), 0) for q in QOS_NAMES}
        self._queue: collections.deque[QueuedRequest] = collections.deque()
        self._class_depth = {q: 0 for q in QOS_NAMES}
        self._evicted: list[QueuedRequest] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        # EWMA of per-batch service seconds (compute + reply writes).
        self._svc_ewma = max(float(service_time_init_ms), 0.1) / 1000.0
        self.stats = GateStats()
        self._draining = False

    # -- estimates -----------------------------------------------------
    def service_estimate(self) -> float:
        with self._lock:
            return self._svc_ewma

    def note_batch(self, service_sec: float) -> None:
        """Batcher feedback: fold one batch's service time into the
        EWMA the wait estimates are built from."""
        with self._lock:
            self._svc_ewma += 0.2 * (max(service_sec, 0.0)
                                     - self._svc_ewma)

    def _wait_estimate_locked(self, depth: int) -> float:
        """Expected queue wait at ``depth`` queued requests: the number
        of batches ahead times the rolling batch service time."""
        batches_ahead = (depth + self.batch_max - 1) // self.batch_max
        return batches_ahead * self._svc_ewma

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- admission (accept-thread side) --------------------------------
    def _cstats(self, qos: int) -> dict:
        return self.stats.per_class[QOS_NAMES.get(qos, "bronze")]

    def submit(self, req: QueuedRequest
               ) -> tuple[str, int]:
        """Admit or shed one arrival.  Returns ``(verdict,
        retry_after_ms)`` where verdict is ``"admitted"`` /
        ``"shed_queue_full"`` / ``"shed_deadline"`` /
        ``"draining"`` — the caller sends the typed reply for the
        non-admitted verdicts.  Pure function of the gate state at the
        call (determinism contract above).

        Eviction victims (a higher-class arrival displacing queued
        lower-class work at a full queue) do not surface here — the
        caller collects them via :meth:`pop_evicted` and answers each
        with its own typed shed reply."""
        now = req.arrival
        with self._lock:
            if self._draining:
                # A submit racing drain() must never land in the
                # already-flushed queue (nobody would ever answer it):
                # the caller sends the typed DRAINING reply instead.
                return "draining", 0
            cls = self._cstats(req.qos)
            cls["offered"] += 1
            depth = len(self._queue)
            budget = self.qos_budgets.get(req.qos, self.queue_max)
            if self._class_depth.get(req.qos, 0) >= budget:
                # The class spent its own budget: shed within-class,
                # no eviction — a class can never displace itself.
                self.stats.shed_queue_full += 1
                cls["shed_queue_full"] += 1
                retry = self._wait_estimate_locked(depth)
                return "shed_queue_full", max(int(retry * 1000), 1)
            if depth >= self.queue_max:
                victim = self._evict_lower_locked(req.qos)
                if victim is None:
                    self.stats.shed_queue_full += 1
                    cls["shed_queue_full"] += 1
                    retry = self._wait_estimate_locked(depth)
                    return "shed_queue_full", max(int(retry * 1000), 1)
                depth = len(self._queue)
            wait = self._wait_estimate_locked(depth + 1)
            if req.deadline is not None and now + wait > req.deadline:
                self.stats.shed_deadline += 1
                cls["shed_deadline"] += 1
                return "shed_deadline", max(int(wait * 1000), 1)
            self._queue.append(req)
            self._class_depth[req.qos] = \
                self._class_depth.get(req.qos, 0) + 1
            self.stats.admitted += 1
            cls["admitted"] += 1
            self._not_empty.notify()
            return "admitted", 0

    def _evict_lower_locked(self, qos: int) -> QueuedRequest | None:
        """Evict the newest queued request of the LOWEST strictly
        lower class to make room at a full queue; None when no such
        victim exists.  Lowest class first is the shed order the
        classes promise (bronze before silver before gold); newest
        within the class keeps the victim's wasted queue time minimal
        and preserves FIFO order among survivors."""
        best = -1
        for i in range(len(self._queue) - 1, -1, -1):
            cand = self._queue[i]
            if cand.qos < qos and (best < 0
                                   or cand.qos < self._queue[best].qos):
                best = i
        if best < 0:
            return None
        victim = self._queue[best]
        del self._queue[best]
        self._class_depth[victim.qos] -= 1
        victim.shed = "evicted"
        self._evicted.append(victim)
        self.stats.shed_evicted += 1
        self._cstats(victim.qos)["shed_evicted"] += 1
        return victim

    def pop_evicted(self) -> list[QueuedRequest]:
        """Drain the eviction victims accumulated since the last call;
        the caller answers each with a typed shed reply."""
        with self._lock:
            out, self._evicted = self._evicted, []
            return out

    # -- batch formation (batcher-thread side) -------------------------
    def take_batch(self, poll_sec: float = 0.05
                   ) -> tuple[list[QueuedRequest], list[QueuedRequest]]:
        """Block until a batch is ready (or ``poll_sec`` passes empty);
        returns ``(batch, expired)``.

        Formation: wait for the first request, then keep filling until
        ``batch_max`` or ``batch_wait`` past the FIRST member's
        admission.  Requests whose deadline expired while queued land
        in ``expired`` (the shed-before-compute rule) and never count
        toward the batch."""
        with self._not_empty:
            if not self._queue:
                self._not_empty.wait(poll_sec)
                if not self._queue:
                    return [], []
            head = self._queue[0]
            close_at = head.arrival + self.batch_wait
            while (len(self._queue) < self.batch_max
                   and not self._draining):
                left = close_at - time.monotonic()
                if left <= 0:
                    break
                self._not_empty.wait(left)
            batch: list[QueuedRequest] = []
            expired: list[QueuedRequest] = []
            now = time.monotonic()
            while self._queue and len(batch) < self.batch_max:
                req = self._queue.popleft()
                self._class_depth[req.qos] -= 1
                if req.deadline is not None and now > req.deadline:
                    req.shed = "timeout"
                    self.stats.timed_out += 1
                    self._cstats(req.qos)["timed_out"] += 1
                    expired.append(req)
                else:
                    batch.append(req)
            return batch, expired

    # -- drain ---------------------------------------------------------
    def drain(self) -> list[QueuedRequest]:
        """Stop batching semantics (scale-down / health gate): flush
        and return everything still queued so the server can answer
        each with the typed DRAINING reply."""
        with self._lock:
            self._draining = True
            out = list(self._queue)
            self._queue.clear()
            self._class_depth = {q: 0 for q in QOS_NAMES}
            self._not_empty.notify_all()
        return out

    @property
    def draining(self) -> bool:
        return self._draining
