"""``python -m rabit_tpu.serve.run`` — one serving rank.

A thin module entry kept OUT of the package ``__init__`` import graph
so runpy never sees the target module pre-imported (the
double-import RuntimeWarning ``-m rabit_tpu.serve.server`` would
print).  All behavior lives in :mod:`rabit_tpu.serve.server`.
"""
import sys

from rabit_tpu.serve.server import main

if __name__ == "__main__":
    sys.exit(main())
