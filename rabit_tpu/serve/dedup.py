"""Bounded duplicate suppression for hedged requests (doc/serving.md).

A hedged retry is the standard tail-latency move: fire a second copy of
a slow request at another rank and take whichever answers first.  The
hazard is the *storm* — every copy that loses the race still lands on a
server, and without suppression each one burns model FLOPs and, worse,
each one is reported as a served request, so fleet-wide books stop
balancing ("offered 1000, served 1017").

The :class:`DedupWindow` is the server-side half of the contract.  It
is an **idempotency cache** keyed by the client-chosen ``idem_key``:

* ``claim(key)`` — called at admission.  The first claim of a key wins
  the right to serve; every later claim of the same key is told the key
  is ``inflight`` (winner not yet committed) or ``committed`` (winner's
  answer is cached) and must answer ``STATUS_DUPLICATE`` instead of
  serving.  A committed claim hands back the cached answer so a retry
  after a lost reply still receives the verified result.
* ``commit(key, version, predictions)`` — called when the winner's OK
  reply is produced; caches the answer for later duplicates.
* ``release(key)`` — called when the winner's request *fails to serve*
  (shed / timeout / error / draining).  The key becomes claimable
  again: a failed first attempt must not poison its own retry.

The window is **bounded** (``capacity`` keys, FIFO eviction of
committed entries first, then inflight) so a hedge storm cannot grow
server memory without limit.  The price of the bound is honest and
documented: once a key is evicted, a very late duplicate of it will be
re-served rather than suppressed — dedup is a tail-latency optimisation
with a window, not an exactly-once guarantee.  The property test in
tests/test_serve_qos.py replays exactly this interleaving.

Scope: the window is **per rank**.  Cross-rank hedges are suppressed
client-side (first-settle-wins accounting in tools/loadgen.py); the
server window exists so retries *to the same rank* — the lost-reply and
storm cases — never double-serve.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

DEFAULT_CAPACITY = 4096

#: claim() states.
NEW = "new"
INFLIGHT = "inflight"
COMMITTED = "committed"


class DedupWindow:
    """Bounded first-to-commit-wins idempotency cache.

    Thread-safe: admission claims from the connection threads race with
    commits/releases from the batch thread.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"dedup capacity must be >= 1: {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # key -> None (inflight) | (version, predictions) (committed);
        # insertion order doubles as eviction order.
        self._entries: OrderedDict[int, tuple | None] = OrderedDict()
        self.claims = 0
        self.duplicates = 0
        self.commits = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def claim(self, key: int) -> tuple[str, tuple | None]:
        """Try to win the right to serve ``key``.

        Returns ``(state, cached)``: ``("new", None)`` — caller owns the
        serve; ``("inflight", None)`` — another copy owns it, answer
        Duplicate with no payload; ``("committed", (version, preds))``
        — answer Duplicate with the cached result.
        """
        with self._lock:
            self.claims += 1
            if key in self._entries:
                self.duplicates += 1
                cached = self._entries[key]
                return (COMMITTED, cached) if cached is not None \
                    else (INFLIGHT, None)
            self._evict_locked()
            self._entries[key] = None
            return NEW, None

    def commit(self, key: int, version: int,
               predictions: np.ndarray) -> None:
        """Cache the winner's OK answer for later duplicates."""
        with self._lock:
            if key in self._entries:
                self._entries[key] = (int(version),
                                      np.asarray(predictions))
                self.commits += 1

    def release(self, key: int) -> None:
        """Forget a claim whose serve failed; the key may retry."""
        with self._lock:
            self._entries.pop(key, None)

    def _evict_locked(self) -> None:
        """Make room for one more entry.

        Committed entries go first (their client already has an
        answer); an inflight entry is evicted only when the whole
        window is inflight — at that point suppressing a storm matters
        less than bounding memory, and the degradation (a re-serve) is
        the documented cost of the bound.
        """
        while len(self._entries) >= self.capacity:
            victim = None
            for k, v in self._entries.items():
                if v is not None:
                    victim = k
                    break
            if victim is None:
                victim = next(iter(self._entries))
            del self._entries[victim]
            self.evictions += 1

    def stats(self) -> dict:
        with self._lock:
            return {"capacity": self.capacity,
                    "entries": len(self._entries),
                    "claims": self.claims,
                    "duplicates": self.duplicates,
                    "commits": self.commits,
                    "evictions": self.evictions}
