"""Distributed gradient-boosted trees over the histogram allreduce.

The reference library's historical role is the collective inside
XGBoost: workers hold row shards, build per-node gradient histograms,
and Allreduce<Sum> them so every worker picks the same split
(rabit-learn ships the collective; the booster lived in XGBoost).  This
module closes that loop with a compact binned GBDT so the histogram
path is exercised end-to-end as a real app: logistic or squared loss,
level-wise trees, split gain from second-order statistics.

TPU-native notes: features are quantile-binned once (int32 on device);
per-node histograms come from the MXU one-hot contraction in
:mod:`rabit_tpu.learn.histogram` with node membership folded into the
grad/hess operand (static shapes — no gather/partition per node).  The
only cross-rank traffic per level is one histogram allreduce per node,
the XGBoost wire pattern.  Fault tolerance: one checkpoint per boosting
round, the reference's per-iteration commit structure.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

import rabit_tpu
from rabit_tpu.learn import histogram
from rabit_tpu.ops import MAX, SUM
from rabit_tpu.utils.checks import check


@dataclass
class TreeNode:
    feature: int = -1          # -1 = leaf
    bin_threshold: int = 0     # go left if bin <= threshold
    value: float = 0.0         # leaf weight
    left: int = -1
    right: int = -1
    # learned default direction for missing values (XGBoost's
    # sparsity-aware split; rows whose bin is the missing bin go this way)
    default_left: bool = True


@dataclass
class BoostedModel:
    """A forest of binned trees + the quantile cuts that define bins."""

    cuts: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 0), np.float32))
    trees: list[list[TreeNode]] = field(default_factory=list)
    base_score: float = 0.0
    learning_rate: float = 0.3
    loss: str = "logistic"
    # does ANY rank's shard carry NaN features?  Decided once at round 0
    # (a collective) and carried in the model: a resumed rank must NOT
    # re-issue that collective — an op the survivors don't issue in the
    # same span would break the robust engine's replay alignment.
    has_missing: bool = False

    def _tree_margin(self, tree: list[TreeNode], bins: np.ndarray
                     ) -> np.ndarray:
        missing_bin = self.cuts.shape[1] + 1
        node = np.zeros(bins.shape[0], np.int32)
        out = np.zeros(bins.shape[0], np.float32)
        live = np.ones(bins.shape[0], bool)
        # level-wise walk: every row sits at some node; descend until leaf
        for _ in range(64):  # depth bound
            if not live.any():
                break
            for nid in np.unique(node[live]):
                n = tree[nid]
                rows = live & (node == nid)
                if n.feature < 0:
                    out[rows] = n.value
                    live[rows] = False
                else:
                    b = bins[rows, n.feature]
                    go_left = np.where(b == missing_bin,
                                       getattr(n, "default_left", True),
                                       b <= n.bin_threshold)
                    idx = np.flatnonzero(rows)
                    node[idx[go_left]] = n.left
                    node[idx[~go_left]] = n.right
        return out

    def margin(self, bins: np.ndarray) -> np.ndarray:
        out = np.full(bins.shape[0], self.base_score, np.float32)
        for tree in self.trees:
            out += self.learning_rate * self._tree_margin(tree, bins)
        return out

    def predict(self, values: np.ndarray) -> np.ndarray:
        bins = apply_cuts(values, self.cuts)
        m = self.margin(bins)
        if self.loss == "logistic":
            return 1.0 / (1.0 + np.exp(-m))
        return m


# re-exported for callers binning prediction-time data
apply_cuts = histogram.apply_cuts


def _grad_hess(margin: np.ndarray, labels: np.ndarray, loss: str):
    if loss == "logistic":
        p = 1.0 / (1.0 + np.exp(-margin))
        return (p - labels).astype(np.float32), (p * (1 - p)).astype(
            np.float32)
    return (margin - labels).astype(np.float32), np.ones_like(margin)


def train(values: np.ndarray, labels: np.ndarray, num_round: int = 10,
          max_depth: int = 3, nbin: int = 32, learning_rate: float = 0.3,
          reg_lambda: float = 1.0, loss: str = "logistic",
          min_child_weight: float = 1e-3,
          subsample: float = 1.0, seed: int = 0,
          use_pallas: bool | None = None,
          compute_dtype: str | None = None) -> BoostedModel:
    """Train a distributed booster on this rank's row shard.

    Deterministic across ranks: cuts come from rank 0, every split
    decision is taken on the allreduced histogram.  Resumes from the
    last committed round after a failure (checkpoint per round).

    ``subsample < 1`` draws a fresh per-round row sample (stochastic
    gradient boosting): sampled-out rows contribute no gradient mass to
    any histogram or leaf this round.  The draw is seeded by
    ``(seed, round, rank)``, so a resumed run replays the exact sample
    of the round it died in — replay stays bit-aligned with survivors.

    NaN feature values are missing: they bin into a dedicated slot,
    every split learns a default direction from the missing rows'
    gradient mass (``histogram.split_gain_missing``), and prediction
    routes NaN the same way — XGBoost's sparsity-aware splits.

    ``use_pallas``/``compute_dtype`` pin the histogram path: on TPU the
    default is the fused Pallas kernel with bf16-rounded weights
    (fastest); reproducibility-sensitive callers can force the exact
    float32 XLA path with ``use_pallas=False`` (bit-identical to CPU)
    or keep the kernel but widen it with ``compute_dtype="float32"``.
    """
    check(0.0 < subsample <= 1.0, "subsample must be in (0, 1], got %s",
          subsample)
    n, f = values.shape
    version, restored = rabit_tpu.load_checkpoint()
    nan_handle = None
    if version == 0:
        # rank 0's shard defines the cuts; other ranks just receive them
        cuts = rabit_tpu.broadcast(
            histogram.quantile_cuts(values, nbin)
            if rabit_tpu.get_rank() == 0 else None, 0)
        # missing handling is GLOBAL: any rank with NaNs means every
        # rank must carry the extra histogram slot and the missing-aware
        # gain.  Decided HERE (round 0) and checkpointed in the model —
        # a resume must not repeat the collective (replay alignment).
        # Issued async with fuse=False (a lone op waiting in a bucket
        # would not start until wait()): the MAX vote rides the wire
        # while this rank runs the big apply_cuts binning pass below.
        nan_handle = rabit_tpu.allreduce_async(
            np.array([np.isnan(values).any()], np.int32), MAX, fuse=False)
        base = 0.0
        model = BoostedModel(cuts=cuts, base_score=base,
                             learning_rate=learning_rate, loss=loss,
                             has_missing=False)
    else:
        model = restored
    bins = apply_cuts(values, model.cuts)
    if nan_handle is not None:
        model.has_missing = bool(nan_handle.wait()[0])
    has_missing = getattr(model, "has_missing", False)
    missing_bin = model.cuts.shape[1] + 1
    margin = model.margin(bins)  # recomputed once on (re)start
    # resident transposed bins: the fused level-histogram kernel streams
    # the (f, n) layout; transpose once, reuse every node/level/round
    import jax
    bins_t = (jax.numpy.asarray(bins).T
              if jax.default_backend() == "tpu" else None)

    epoch = rabit_tpu.device_epoch()
    for round_idx in range(version, num_round):
        if bins_t is not None and rabit_tpu.device_epoch() != epoch:
            # device plane re-formed after a failure: old-epoch arrays
            # died with the backends — re-upload the resident bins
            epoch = rabit_tpu.device_epoch()
            bins_t = jax.numpy.asarray(bins).T
        grad, hess = _grad_hess(margin, labels, model.loss)
        if subsample < 1.0:
            # zeroed grad/hess = row contributes nothing anywhere this
            # round (histograms, depth-limit leaves) while every shape
            # stays static for the fused kernels
            rng = np.random.default_rng(
                (seed, round_idx, rabit_tpu.get_rank()))
            keep = rng.random(n) < subsample
            grad = np.where(keep, grad, 0.0).astype(np.float32)
            hess = np.where(keep, hess, 0.0).astype(np.float32)

        tree: list[TreeNode] = [TreeNode()]
        node_of_row = np.zeros(n, np.int32)
        frontier = [0]
        for depth in range(max_depth):
            next_frontier: list[int] = []
            # every live node's histogram in one fused bins pass and
            # ONE allreduce for the level (the per-node XGBoost wire
            # pattern, batched)
            hists = histogram.build_level_allreduce(
                bins, grad, hess, node_of_row, frontier,
                missing_bin + 1 if has_missing else missing_bin,
                bins_t=bins_t,
                use_pallas=use_pallas, compute_dtype=compute_dtype)
            for pos, nid in enumerate(frontier):
                hist = hists[pos]
                g_tot = hist[:, :, 0].sum(axis=1)[0]
                h_tot = hist[:, :, 1].sum(axis=1)[0]
                leaf_value = -g_tot / (h_tot + reg_lambda)
                if has_missing:
                    gain, default_left = histogram.split_gain_missing(
                        hist, reg_lambda)
                else:
                    gain = histogram.split_gain(hist, reg_lambda)
                    default_left = None
                j, t = np.unravel_index(int(gain.argmax()), gain.shape)
                dl = bool(default_left[j, t]) if has_missing else True
                hl = hist[j, :t + 1, 1].sum()
                if has_missing and dl:
                    hl += hist[j, -1, 1]
                hr = h_tot - hl
                if (gain[j, t] <= 1e-12 or hl < min_child_weight
                        or hr < min_child_weight):
                    tree[nid].value = float(leaf_value)
                    continue
                node = tree[nid]
                node.feature = int(j)
                node.bin_threshold = int(t)
                node.default_left = dl
                node.left = len(tree)
                tree.append(TreeNode())
                node.right = len(tree)
                tree.append(TreeNode())
                rows = node_of_row == nid
                b = bins[:, j]
                go_left = np.where(b == missing_bin, dl, b <= t)
                node_of_row[rows & go_left] = node.left
                node_of_row[rows & ~go_left] = node.right
                next_frontier += [node.left, node.right]
            frontier = next_frontier
            if not frontier:
                break
        # frontier nodes at max depth become leaves: one batched
        # allreduce of all their (g, h) sums (not one per leaf)
        if frontier:
            gh = np.empty((len(frontier), 2), np.float64)
            for i, nid in enumerate(frontier):
                mask = node_of_row == nid
                gh[i] = (grad[mask].sum(), hess[mask].sum())
            gh = rabit_tpu.allreduce(gh.reshape(-1), SUM).reshape(-1, 2)
            for i, nid in enumerate(frontier):
                tree[nid].value = float(-gh[i, 0] / (gh[i, 1] + reg_lambda))
        model.trees.append(tree)
        margin += model.learning_rate * model._tree_margin(tree, bins)
        rabit_tpu.checkpoint(model)
    return model
