"""XGBoost-style gradient-histogram building + allreduce.

The reference's historical raison d'être is the histogram allreduce
inside XGBoost: each worker bins its feature shard, accumulates per
(feature, bin) gradient/hessian sums for the tree node being split, and
Allreduce<Sum>'s the flat histogram so every worker sees the global
statistics (the pattern BASELINE.md lists under "configs to reproduce";
the reference itself only ships the collective, the histogram is the
app's job — same split here).

TPU-native design: binned features live on device as an (n, f) int32
array; the builder is a single jitted program that scans (row-block,
feature-block) tiles, expanding bins to a one-hot against a bin iota and
contracting with the (grad, hess) pair on the MXU — compiler-friendly
fixed shapes, no scatter (TPU scatters serialize; the one-hot contraction
keeps the FLOPs on the matrix unit).  The cross-worker step is one
framework allreduce of the flat (f * nbin * 2) histogram, exactly the
XGBoost wire pattern.
"""
from __future__ import annotations

import numpy as np

import rabit_tpu
from rabit_tpu.ops import SUM

_CACHE: dict = {}


def _writable(arr) -> "np.ndarray":
    """Host-allreduce input prep: the collective is in-place by
    contract (include/rabit.h:134-137) but jax arrays export read-only
    buffers — copy exactly when the local build handed us one."""
    arr = np.asarray(arr)
    if not arr.flags.writeable:
        arr = arr.copy()
    return arr

DEFAULT_ROW_BLOCK = 8192
DEFAULT_FEAT_BLOCK = 8


def quantile_cuts(values: np.ndarray, nbin: int) -> np.ndarray:
    """Per-column quantile cut points, shape (f, nbin - 1) — the
    host-side analogue of XGBoost's quantile sketch (per-shard; callers
    needing globally consistent cuts broadcast/allreduce them).

    NaN entries are missing values: cuts come from the present entries
    only (``nanquantile``) — plain ``quantile`` would poison a whole
    column's cuts to NaN.  An all-NaN column gets zero cuts (every
    present-at-predict-time value bins to 0; its rows ride the missing
    bin anyway)."""
    qs = np.linspace(0, 1, nbin + 1)[1:-1]
    with np.errstate(all="ignore"):
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            cuts = np.nanquantile(values, qs, axis=0).T
    return np.nan_to_num(cuts, nan=0.0).astype(np.float32)


def apply_cuts(values: np.ndarray, cuts: np.ndarray) -> np.ndarray:
    """Bin raw feature values with quantile cuts → int32 in [0, nbin);
    NaN (missing) values map to the dedicated bin ``nbin`` one past the
    regular range, so histogram builders can tally missing-row gradient
    mass per feature and the booster can learn a per-split default
    direction (XGBoost's sparsity-aware split semantics)."""
    n, f = values.shape
    bins = np.empty((n, f), np.int32)
    for j in range(f):
        bins[:, j] = np.searchsorted(cuts[j], values[:, j], side="right")
    nan = np.isnan(values)
    if nan.any():
        bins[nan] = cuts.shape[1] + 1
    return bins


def split_gain_missing(hist: np.ndarray, reg_lambda: float = 1.0):
    """Sparsity-aware split gain: the LAST bin of ``hist`` (f, nbin, 2)
    holds the missing-value rows.  For every (feature, cut) the gain is
    evaluated with the missing mass sent left and sent right; returns
    ``(gain, default_left)`` where gain is the better of the two and
    default_left says which direction won (XGBoost's learned default
    direction, one bool per candidate split)."""
    g, h = hist[:, :-1, 0], hist[:, :-1, 1]
    gm = hist[:, -1:, 0]
    hm = hist[:, -1:, 1]
    gl = np.cumsum(g, axis=1)[:, :-1]
    hl = np.cumsum(h, axis=1)[:, :-1]
    gt = g.sum(axis=1, keepdims=True) + gm
    ht = h.sum(axis=1, keepdims=True) + hm
    parent = gt * gt / (ht + reg_lambda)

    def score(gl_, hl_):
        gr_, hr_ = gt - gl_, ht - hl_
        return (gl_ * gl_ / (hl_ + reg_lambda)
                + gr_ * gr_ / (hr_ + reg_lambda) - parent)

    gain_left = score(gl + gm, hl + hm)    # missing goes left
    gain_right = score(gl, hl)             # missing goes right
    return np.maximum(gain_left, gain_right), gain_left >= gain_right


def quantize(values: np.ndarray, nbin: int):
    """Quantile-bin each feature column; returns (bins, cuts)."""
    cuts = quantile_cuts(values, nbin)
    return apply_cuts(values, cuts), cuts


def _builder(n: int, f: int, nbin: int, row_block: int, feat_block: int):
    """Jitted histogram builder.

    Formulation chosen by measurement on TPU: one (n, nbin) one-hot per
    feature contracted with the packed (n, 2) grad/hess operand on the
    MXU.  Scatter-adds are ~1000x slower on TPU (they serialize) and a
    blocked einsum defeats XLA's fusion; per-feature matmuls stream at
    HBM bandwidth.  Features are processed ``feat_block`` at a time
    inside a ``lax.scan`` — unrolled within a chunk for speed, scanned
    across chunks to bound compile time.  ``row_block`` is accepted for
    API stability but the contraction is over all rows at once.
    """
    key = (n, f, nbin, row_block, feat_block)
    fn = _CACHE.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        nfb = -(-f // feat_block)
        fpad = nfb * feat_block

        @jax.jit
        def build(bins, grad, hess):
            # pad features with bin -1 (matches no one-hot lane); pack
            # (grad, hess) as one (n, 2) operand for a single contraction
            b = jnp.full((n, fpad), -1, jnp.int32).at[:, :f].set(bins)
            gh = jnp.stack([grad, hess], axis=1)       # (n, 2)
            iota = jnp.arange(nbin, dtype=jnp.int32)

            def chunk(_, bcols):                        # (n, feat_block)
                parts = []
                for j in range(feat_block):
                    oh = (bcols[:, j][:, None] == iota).astype(jnp.float32)
                    parts.append(jax.lax.dot_general(
                        oh, gh, (((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32))
                return None, jnp.stack(parts)           # (feat_block, nbin, 2)

            _, out = jax.lax.scan(
                chunk, None,
                b.reshape(n, nfb, feat_block).transpose(1, 0, 2))
            return out.reshape(fpad, nbin, 2)[:f]

        _CACHE[key] = build
        fn = build
    return fn


def build_local(bins, grad, hess, nbin: int,
                row_block: int = DEFAULT_ROW_BLOCK,
                feat_block: int = DEFAULT_FEAT_BLOCK,
                use_pallas: bool | None = None,
                compute_dtype=None) -> np.ndarray:
    """Local (f, nbin, 2) histogram of (grad, hess) sums on device.

    Measured on TPU with chained difference timing (the only honest
    method through the tunnel — doc/benchmarks.md): the fused Pallas
    kernel (:mod:`rabit_tpu.ops.histogram_kernel`) runs a single
    histogram in ~0.8 ms vs ~30 ms for the XLA one-hot contraction
    (~37x), so it is the default on TPU; off-TPU the XLA path is used
    (``use_pallas=True`` forces interpret mode for tests).  Per-node
    level builds share one bins pass — see :func:`build_level_local`.
    ``compute_dtype`` bounds the kernel's weight rounding (default
    bf16; one-hots are exact).
    """
    import jax
    import jax.numpy as jnp

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from rabit_tpu.ops.histogram_kernel import hist_fused
        kw = {} if compute_dtype is None else {"compute_dtype": compute_dtype}
        return hist_fused(bins, grad, hess, nbin, **kw)
    n, f = bins.shape
    fn = _builder(n, f, nbin, row_block, feat_block)
    return fn(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess))


def build_level_local(bins, grad, hess, node_of_row, node_ids,
                      nbin: int, bins_t=None, use_pallas: bool | None = None,
                      compute_dtype=None):
    """(m, f, nbin, 2) per-node histograms for one tree level.

    Level-wise boosting needs one histogram per live node; building
    them one at a time re-reads the (n, f) bins array per node.  On
    TPU this routes every node through ONE fused-kernel bins pass
    (measured ~25x over per-node XLA passes at 8 nodes,
    doc/benchmarks.md):
    :func:`rabit_tpu.ops.histogram_kernel.hist_fused_multi` with a
    (2m, n) weight matrix — node masks folded into grad/hess channels,
    chunked when a level exceeds the kernel's channel budget.
    ``bins_t`` optionally supplies the resident transposed (f, n)
    device array so the transpose isn't redone per level.  Off-TPU,
    falls back to the XLA builder per node.
    """
    import jax
    import jax.numpy as jnp

    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    nid = jnp.asarray(np.asarray(node_ids, np.int32))
    nor = jnp.asarray(np.asarray(node_of_row, np.int32))
    g = jnp.asarray(grad)
    h = jnp.asarray(hess)
    m = len(node_ids)
    if use_pallas:
        from rabit_tpu.ops import histogram_kernel as hk
        if bins_t is None:
            bins_t = jnp.asarray(bins).T
        kw = {} if compute_dtype is None else {"compute_dtype": compute_dtype}
        # chunk derived from the kernel's VMEM accumulator budget (2
        # channels per node: grad + hess), not a fixed constant — wide
        # features shrink it so deep levels still compile
        chunk = max(1, hk.max_channels(nbin, bins.shape[1]) // 2)
        outs = []
        for lo_i in range(0, m, chunk):
            nids = nid[lo_i:lo_i + chunk]
            mc = len(nids)
            mask = (nor[None, :] == nids[:, None]).astype(g.dtype)
            w = jnp.concatenate([mask * g[None, :], mask * h[None, :]])
            out = hk.hist_fused_multi(bins_t, w, nbin, **kw)  # (2mc, f, nbin)
            outs.append(jnp.stack([out[:mc], out[mc:]], axis=-1))
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs)
    g_np, h_np, nor_np = np.asarray(g), np.asarray(h), np.asarray(nor)
    parts = [build_local(bins, g_np * (nor_np == v), h_np * (nor_np == v),
                         nbin, use_pallas=False)
             for v in np.asarray(node_ids)]
    return jnp.stack([jnp.asarray(p) for p in parts])


def build_level_allreduce(bins, grad, hess, node_of_row, node_ids,
                          nbin: int, **kw) -> np.ndarray:
    """Global per-node level histograms: one local fused pass + ONE
    framework Allreduce<Sum> for the whole level (vs one per node).

    Under the XLA engine the payload stays a device array so the
    reduction rides the device data plane (ICI) like the kmeans stats
    matrix does; host engines take the fault-tolerant numpy path."""
    from rabit_tpu import engine as _engine_mod

    local = build_level_local(
        bins, grad, hess, node_of_row, node_ids, nbin, **kw)
    if not _engine_mod.is_device_plane():
        local = _writable(local)  # fault-tolerant host path
    shape = local.shape
    out = rabit_tpu.allreduce(local.reshape(-1), SUM)
    return np.asarray(out).reshape(shape)


def build_allreduce(bins, grad, hess, nbin: int, **kw) -> np.ndarray:
    """Global histogram: local build + framework Allreduce<Sum> of the
    flat payload (the XGBoost per-split wire pattern).

    Histogram sums deliberately stay opted IN to an armed lossy wire
    codec (``rabit_wire_codec``, doc/performance.md): split decisions
    compare aggregate (g, h) sums whose ordering survives one
    quantization step, and the error-feedback stream compensates
    across the repeated per-level allreduces — this is the bulk
    traffic the codec exists for."""
    local = _writable(build_local(bins, grad, hess, nbin, **kw))
    shape = local.shape
    out = rabit_tpu.allreduce(local.reshape(-1), SUM)
    return out.reshape(shape)


class HistogramHandle:
    """Waitable result of :func:`build_allreduce_async`; ``wait()``
    returns the reduced (f, nbin, 2) histogram."""

    def __init__(self, handle, shape):
        self._handle = handle
        self._shape = shape

    def wait(self) -> np.ndarray:
        return np.asarray(self._handle.wait()).reshape(self._shape)


def build_allreduce_async(bins, grad, hess, nbin: int, fuse: bool = False,
                          **kw) -> HistogramHandle:
    """Async :func:`build_allreduce`: the flat histogram rides an engine
    handle so the caller overlaps independent compute (the next node's
    local build, gain scans of already-reduced histograms) with the
    wire.  ``fuse`` defaults to False — the single-call pattern
    (issue, compute, wait) needs eager dispatch, since a bucketed op
    only reaches the wire when its bucket flushes; pass ``fuse=True``
    when issuing a back-to-back stream of per-node histograms so they
    coalesce under ``rabit_bucket_bytes`` (doc/performance.md).
    Host-path variant: the payload is pulled to numpy, so on the XLA
    engine it routes through the inner host transport rather than ICI —
    use :func:`build_level_allreduce` for the device-plane level
    batch."""
    local = _writable(build_local(bins, grad, hess, nbin, **kw))
    handle = rabit_tpu.allreduce_async(local.reshape(-1), SUM, fuse=fuse)
    return HistogramHandle(handle, local.shape)


def split_gain(hist: np.ndarray, reg_lambda: float = 1.0) -> np.ndarray:
    """Per (feature, cut) split gain from a (f, nbin, 2) histogram —
    the standard XGBoost structure score, vectorized over all cuts."""
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    gl = np.cumsum(g, axis=1)[:, :-1]
    hl = np.cumsum(h, axis=1)[:, :-1]
    gt = g.sum(axis=1, keepdims=True)
    ht = h.sum(axis=1, keepdims=True)
    gr, hr = gt - gl, ht - hl
    parent = gt * gt / (ht + reg_lambda)
    return (gl * gl / (hl + reg_lambda)
            + gr * gr / (hr + reg_lambda) - parent)
