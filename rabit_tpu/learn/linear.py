"""Linear / logistic regression on the L-BFGS solver.

Equivalent of reference: rabit-learn/linear/{linear.h,linear.cc}.  The
objective's Eval/CalcGrad — the FLOP-heavy part the reference spreads over
OpenMP threads with per-row sparse loops (linear.cc:150-201) — are here
single jitted XLA programs over the padded-ELL data: margins come from a
gather + row-sum, gradients from a scatter-add, both fused by XLA.  Model
files keep the reference's two on-disk encodings ("binf" binary and
"bs64" base64 text for text-only channels, linear.cc:76-122).
"""
from __future__ import annotations

import struct
import sys
from typing import BinaryIO

import numpy as np

import rabit_tpu
from rabit_tpu.learn.data import SparseMat, load_libsvm
from rabit_tpu.learn.lbfgs import LBFGSSolver, ObjFunction
from rabit_tpu.ops import MAX
from rabit_tpu.utils.checks import check
from rabit_tpu.utils.serial import Base64InStream, Base64OutStream

LOSS_LINEAR = 0
LOSS_LOGISTIC = 1

# on-disk param block: base_score, num_feature, loss_type + reserved pad
# (layout of reference ModelParam, linear.h:18-33; fixed little-endian here)
_PARAM_FMT = "<fQi64x"


class LinearModel:
    """Weights + param block (reference: LinearModel, linear.h:17-130).

    ``weight`` has ``num_feature + 1`` entries; the last is the bias.
    """

    def __init__(self) -> None:
        self.base_score = 0.5
        self.num_feature = 0
        self.loss_type = LOSS_LOGISTIC
        self.weight: np.ndarray | None = None

    # -- config (reference: ModelParam::SetParam, linear.h:45-62) ----------
    def set_param(self, name: str, val: str) -> None:
        if name == "base_score":
            self.base_score = float(val)
        elif name == "num_feature":
            self.num_feature = int(val)
        elif name == "objective":
            if val == "linear":
                self.loss_type = LOSS_LINEAR
            elif val == "logistic":
                self.loss_type = LOSS_LOGISTIC
            else:
                check(False, "unknown objective type %s", val)

    def init_base_score(self) -> None:
        """Fold base_score through the logit once at init
        (reference: linear.h:35-39)."""
        check(0.0 < self.base_score < 1.0,
              "base_score must be in (0,1) for logistic loss")
        self.base_score = -float(np.log(1.0 / self.base_score - 1.0))

    # -- inference ---------------------------------------------------------
    def margin(self, data: SparseMat, weight: np.ndarray | None = None
               ) -> np.ndarray:
        w = self.weight if weight is None else weight
        nf = self.num_feature
        out = np.full(data.num_row, self.base_score + w[nf], np.float64)
        for i in range(data.num_row):
            fi, fv = data.row(i)
            keep = fi < nf
            out[i] += w[fi[keep]] @ fv[keep]
        return out

    def predict(self, data: SparseMat) -> np.ndarray:
        m = self.margin(data)
        if self.loss_type == LOSS_LOGISTIC:
            return 1.0 / (1.0 + np.exp(-m))
        return m

    # -- model IO (reference: LinearModel::Load/Save, linear.h:114-126;
    #    headers written by linear.cc:76-122) ------------------------------
    def _save_stream(self, write) -> None:
        write(struct.pack(_PARAM_FMT, self.base_score, self.num_feature,
                          self.loss_type))
        write(np.asarray(self.weight, np.float32).tobytes())

    def _load_stream(self, read) -> None:
        hdr = read(struct.calcsize(_PARAM_FMT))
        self.base_score, self.num_feature, self.loss_type = struct.unpack(
            _PARAM_FMT, hdr)
        raw = read(4 * (self.num_feature + 1))
        self.weight = np.frombuffer(raw, np.float32).astype(np.float64)

    def save(self, fname: str, base64_: bool = False) -> None:
        use_stdout = fname == "stdout"
        fp: BinaryIO = sys.stdout.buffer if use_stdout else open(fname, "wb")
        try:
            if base64_ or use_stdout:
                fp.write(b"bs64\t")
                out = Base64OutStream(fp)
                self._save_stream(out.write)
                out.finish()
                fp.write(b"\n")
            else:
                fp.write(b"binf")
                self._save_stream(fp.write)
        finally:
            if not use_stdout:
                fp.close()

    def load(self, fname: str) -> None:
        with open(fname, "rb") as fp:
            header = fp.read(4)
            if header == b"bs64":
                fp.read(1)  # tab
                self._load_stream(Base64InStream(fp).read)
            elif header == b"binf":
                self._load_stream(fp.read)
            else:
                check(False, "invalid model file")


_EVAL_CACHE: dict = {}


def _make_kernels(loss_type: int, nblocks: int, block: int, nnz: int,
                  wlen: int):
    """Jitted eval/grad over ELL blocks.

    Weights are padded with one zero slot that all ELL padding (and any
    feature ≥ num_feature, reference: linear.h:94-96) points at, so the
    gather/scatter needs no masking.
    """
    key = (loss_type, nblocks, block, nnz, wlen)
    fns = _EVAL_CACHE.get(key)
    if fns is not None:
        return fns
    import jax
    import jax.numpy as jnp

    def margins(wpad, base, idx, val):
        # (nb, B, nnz) gather → row-sum; bias wpad[wlen-2] added by caller
        return base + jnp.sum(wpad[idx] * val, axis=-1)

    @jax.jit
    def eval_fn(wpad, base, idx, val, labels, valid):
        m = margins(wpad, base, idx, val)
        if loss_type == LOSS_LOGISTIC:
            # stable nlogprob (reference: MarginToLoss, linear.h:72-86)
            nlogprob = jnp.where(
                m > 0.0,
                jnp.log1p(jnp.exp(-m)),
                -m + jnp.log1p(jnp.exp(m)))
            loss = labels * nlogprob + (1.0 - labels) * (m + nlogprob)
        else:
            loss = 0.5 * (m - labels) ** 2
        return jnp.sum(loss * valid)

    @jax.jit
    def grad_fn(wpad, base, idx, val, labels, valid):
        m = margins(wpad, base, idx, val)
        if loss_type == LOSS_LOGISTIC:
            pred = jax.nn.sigmoid(m)
        else:
            pred = m
        g = (pred - labels) * valid          # (nb, B)
        flat_idx = idx.reshape(-1)
        flat = (val * g[..., None]).reshape(-1)
        # 1-D scatter into the weight vector measures on par with a
        # one-hot contraction here (unlike the 2-D row densify in
        # kmeans, where one-hot wins 10x) — keep the simple form.
        gw = jnp.zeros(wlen, jnp.float32).at[flat_idx].add(flat)
        return gw, jnp.sum(g)

    _EVAL_CACHE[key] = (eval_fn, grad_fn)
    return _EVAL_CACHE[key]


class LinearObjFunction(ObjFunction):
    """The solver-facing objective (reference: LinearObjFunction,
    linear.cc:7-208)."""

    def __init__(self) -> None:
        self.model = LinearModel()
        self.reg_L2 = 0.0
        self.task = "train"
        self.model_in = "NULL"
        self.model_out = "final.model"
        self.name_pred = "pred.txt"
        self.save_base64 = False
        self.row_block = 1024
        self.lbfgs = LBFGSSolver(self)
        self.dtrain: SparseMat | None = None
        self._ell = None

    # ------------------------------------------------------------------
    def set_param(self, name: str, val: str) -> None:
        self.model.set_param(name, val)
        self.lbfgs.set_param(name, val)
        if name == "num_feature":
            self.lbfgs.set_param("num_dim", str(int(val) + 1))
        elif name == "reg_L2":
            self.reg_L2 = float(val)
        elif name == "task":
            self.task = val
        elif name == "model_in":
            self.model_in = val
        elif name == "model_out":
            self.model_out = val
        elif name == "name_pred":
            self.name_pred = val
        elif name == "save_base64":
            self.save_base64 = bool(int(val))
        elif name == "row_block":
            self.row_block = int(val)

    def load_data(self, fname: str) -> None:
        self.dtrain = load_libsvm(fname)

    # ------------------------------------------------------------------
    # ObjFunction contract
    def init_num_dim(self) -> int:
        """(reference: InitNumDim, linear.cc:126-133)"""
        if self.model_in == "NULL":
            ndim = int(rabit_tpu.allreduce(
                np.array([self.dtrain.feat_dim], np.int64), MAX)[0])
            self.model.num_feature = max(ndim, self.model.num_feature)
        return self.model.num_feature + 1

    def init_model(self, weight: np.ndarray) -> None:
        """(reference: InitModel, linear.cc:134-142)"""
        if self.model_in == "NULL":
            weight[:] = 0.0
            if self.model.loss_type == LOSS_LOGISTIC:
                self.model.init_base_score()
        else:
            weight[:] = self.model.weight

    def save_state(self) -> object:
        return (self.model.base_score, self.model.num_feature,
                self.model.loss_type)

    def load_state(self, state: object) -> None:
        (self.model.base_score, self.model.num_feature,
         self.model.loss_type) = state

    def _ell_blocks(self):
        if self._ell is None:
            nf = self.model.num_feature
            idx, val, labels, valid = self.dtrain.to_ell(
                pad_index=nf + 1, row_block=self.row_block)
            # any feature ≥ num_feature routes to the zero pad slot
            idx = np.where(idx >= nf, nf + 1, idx).astype(np.int32)
            import jax

            nb = idx.shape[0] // self.row_block
            # device-resident across all solver iterations
            self._ell = tuple(jax.device_put(a) for a in (
                idx.reshape(nb, self.row_block, -1),
                val.reshape(nb, self.row_block, -1),
                labels.reshape(nb, self.row_block),
                valid.reshape(nb, self.row_block),
            ))
        return self._ell

    def _wpad(self, weight: np.ndarray) -> np.ndarray:
        # [w_0..w_{nf-1}, bias, 0-pad]
        return np.concatenate(
            [weight, [0.0]]).astype(np.float32)

    def eval(self, weight: np.ndarray) -> float:
        """Shard data loss (+L2 on rank 0 only — added once globally;
        reference: Eval, linear.cc:150-173)."""
        idx, val, labels, valid = self._ell_blocks()
        eval_fn, _ = _make_kernels(
            self.model.loss_type, *idx.shape, len(weight) + 1)
        nf = self.model.num_feature
        base = np.float32(self.model.base_score + weight[nf])
        sum_val = float(eval_fn(self._wpad(weight), base, idx, val,
                                labels, valid))
        if rabit_tpu.get_rank() == 0 and self.reg_L2 != 0.0:
            sum_val += 0.5 * self.reg_L2 * float(weight[:nf] @ weight[:nf])
        check(not np.isnan(sum_val), "nan occurs")
        return sum_val

    def calc_grad(self, weight: np.ndarray) -> np.ndarray:
        """Shard gradient (reference: CalcGrad, linear.cc:174-201)."""
        idx, val, labels, valid = self._ell_blocks()
        _, grad_fn = _make_kernels(
            self.model.loss_type, *idx.shape, len(weight) + 1)
        nf = self.model.num_feature
        base = np.float32(self.model.base_score + weight[nf])
        gw, gbias = grad_fn(self._wpad(weight), base, idx, val,
                            labels, valid)
        out = np.asarray(gw, np.float64)[:nf + 1]
        out[nf] = float(gbias)
        if rabit_tpu.get_rank() == 0 and self.reg_L2 != 0.0:
            out[:nf] += self.reg_L2 * weight[:nf]
        return out

    # ------------------------------------------------------------------
    def run(self) -> None:
        """train / pred dispatch (reference: Run, linear.cc:52-75)."""
        if self.model_in != "NULL":
            self.model.load(self.model_in)
        if self.task == "train":
            self.lbfgs.run()
            w = self.lbfgs.get_weight()
            self.model.weight = np.asarray(w, np.float64)
            if rabit_tpu.get_rank() == 0:
                self.model.save(self.model_out, self.save_base64)
        elif self.task == "pred":
            check(self.model_in != "NULL",
                  "must set model_in for task=pred")
            preds = self.predict()
            with open(self.name_pred, "w") as fp:
                for p in preds:
                    fp.write(f"{p:g}\n")
            print(f"Finishing writing to {self.name_pred}", flush=True)
        else:
            check(False, "unknown task=%s", self.task)

    def predict(self) -> np.ndarray:
        return self.model.predict(self.dtrain)


def main(argv: list[str]) -> int:
    """CLI mirroring the reference binary:
    ``linear <data_in> [name=value ...]`` (reference: linear.cc:212-239)."""
    if len(argv) < 2:
        rabit_tpu.init()
        if rabit_tpu.get_rank() == 0:
            rabit_tpu.tracker_print("Usage: <data_in> param=val")
        rabit_tpu.finalize()
        return 0
    obj = LinearObjFunction()
    if argv[1] == "stdin":
        obj.load_data(argv[1])
        rabit_tpu.init(argv[2:])
    else:
        rabit_tpu.init(argv[2:])
        obj.load_data(argv[1])
    for a in argv[2:]:
        if "=" in a:
            name, val = a.split("=", 1)
            obj.set_param(name, val)
    obj.run()
    rabit_tpu.finalize()
    return 0


def cli() -> int:
    """Console-script entry point."""
    import sys

    return main(sys.argv)


if __name__ == "__main__":
    sys.exit(main(sys.argv))
