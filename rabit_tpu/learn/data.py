"""Data utilities: LibSVM loader and TPU-friendly layouts.

Equivalent of the reference's CSR SparseMat + dense Matrix
(reference: rabit-learn/utils/data.h:23-136), re-designed for XLA:

* Host side the matrix is CSR (numpy ``indptr``/``findex``/``fvalue``).
* For device compute it converts to **padded ELL blocks** — every row
  padded to the same nnz with a sentinel column — so shapes are static
  and kernels jit once regardless of sparsity structure.  The sentinel
  column indexes a zero slot appended to weight/centroid buffers, which
  turns "skip padding" into plain gathers/scatter-adds XLA can fuse.
"""
from __future__ import annotations

import sys
from dataclasses import dataclass, field

import numpy as np

from rabit_tpu.utils.checks import check


@dataclass
class SparseMat:
    """CSR sparse matrix with labels (reference: rabit-learn/utils/data.h:24-100)."""

    indptr: np.ndarray = field(
        default_factory=lambda: np.zeros(1, np.int64))    # (nrow+1,)
    findex: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int32))    # (nnz,)
    fvalue: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.float32))  # (nnz,)
    labels: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.float32))  # (nrow,)
    feat_dim: int = 0

    @property
    def num_row(self) -> int:
        return len(self.indptr) - 1

    @property
    def nnz(self) -> int:
        return len(self.findex)

    def row(self, i: int) -> tuple[np.ndarray, np.ndarray]:
        """(findex, fvalue) of row i."""
        lo, hi = self.indptr[i], self.indptr[i + 1]
        return self.findex[lo:hi], self.fvalue[lo:hi]

    # ---- device layouts --------------------------------------------------
    def to_ell(self, pad_index: int | None = None,
               row_block: int | None = None):
        """Padded ELL arrays ``(indices, values, labels)``.

        ``indices``/``values`` have shape (nrow_padded, max_nnz); padding
        entries carry ``pad_index`` (default: ``feat_dim``, i.e. one past
        the last real feature) and value 0.  When ``row_block`` is given,
        nrow is padded up to a multiple of it (padded rows get label 0 and
        all-padding features) so the data splits into equal static blocks.
        """
        if pad_index is None:
            pad_index = self.feat_dim
        nrow = self.num_row
        counts = np.diff(self.indptr)
        max_nnz = max(1, int(counts.max()) if nrow else 1)
        nrow_pad = nrow
        if row_block:
            nrow_pad = -(-max(nrow, 1) // row_block) * row_block
        uniform = bool(nrow) and self.nnz == nrow * max_nnz
        if uniform and nrow_pad == nrow:
            # Every row has max_nnz entries and no row padding is needed:
            # CSR *is* ELL — reshape, zero copies (matters at the
            # biggest-that-fits scale, where the scatter path below would
            # materialize three extra nnz-sized temporaries).
            idx = np.ascontiguousarray(
                self.findex.reshape(nrow, max_nnz), np.int32)
            val = np.ascontiguousarray(
                self.fvalue.reshape(nrow, max_nnz), np.float32)
        elif uniform:
            idx = np.full((nrow_pad, max_nnz), pad_index, np.int32)
            val = np.zeros((nrow_pad, max_nnz), np.float32)
            idx[:nrow] = self.findex.reshape(nrow, max_nnz)
            val[:nrow] = self.fvalue.reshape(nrow, max_nnz)
        else:
            idx = np.full((nrow_pad, max_nnz), pad_index, np.int32)
            val = np.zeros((nrow_pad, max_nnz), np.float32)
            # CSR→ELL without a Python row loop: flat positions per nnz.
            if self.nnz:
                rows = np.repeat(np.arange(nrow), counts)
                offs = (np.arange(self.nnz)
                        - np.repeat(self.indptr[:-1], counts))
                idx[rows, offs] = self.findex
                val[rows, offs] = self.fvalue
        labels = np.zeros(nrow_pad, np.float32)
        labels[:nrow] = self.labels
        valid = np.zeros(nrow_pad, np.float32)
        valid[:nrow] = 1.0
        return idx, val, labels, valid

    def to_dense(self) -> np.ndarray:
        """Densify (small data / tests only).  Duplicate indices within
        a row ADD — required for hashed features (hash_features), and a
        no-op for ordinary LibSVM rows."""
        out = np.zeros((self.num_row, self.feat_dim), np.float32)
        rows = np.repeat(np.arange(self.num_row), np.diff(self.indptr))
        np.add.at(out, (rows, self.findex), self.fvalue)
        return out


def hash_features(findex: np.ndarray, fvalue: np.ndarray, d_out: int,
                  seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Signed feature hashing: map feature ids into ``[0, d_out)`` with a
    pseudo-random sign on the value (Weinberger et al., "Feature Hashing
    for Large Scale Multitask Learning" — the standard hashing trick;
    the sign makes collision cross-terms zero-mean).

    ``d_out`` must be a power of two (the hash mixes then masks).  Works
    on any integer index array (CSR ``findex`` or padded-ELL blocks —
    pad slots hash somewhere harmless because their value is 0).
    Returns ``(hashed_index, signed_value)``; collisions within a row
    are additive, which every consumer here (dense staging, ELL stats,
    linear models) already handles.

    Why it exists: the sparse k-means kernel's VPU floor is
    ``nnz x 128`` lane-ops/row (doc/benchmarks.md, "ELL kernel plan
    sweep"), while DENSE rows at a hashed width ride the HBM-roofline
    stats kernel — hashing to d_out <= 256 converts the bandwidth-rich
    dense path into an approximate sparse recipe.  Measured tradeoff:
    ``tools/hash_experiments.py``.
    """
    check(d_out > 0 and (d_out & (d_out - 1)) == 0,
          "hash_features: d_out must be a power of two, got %d", d_out)
    h = findex.astype(np.uint32)
    # xorshift-multiply mix (Murmur3 finalizer constants), seed-salted
    h ^= np.uint32((seed * 0x9E3779B9 + 0x85EBCA6B) & 0xFFFFFFFF)
    h ^= h >> np.uint32(16)
    h *= np.uint32(0x85EBCA6B)
    h ^= h >> np.uint32(13)
    h *= np.uint32(0xC2B2AE35)
    h ^= h >> np.uint32(16)
    idx = (h & np.uint32(d_out - 1)).astype(np.int32)
    sign = np.where((h >> np.uint32(31)) & np.uint32(1),
                    np.float32(-1.0), np.float32(1.0))
    return idx, (fvalue.astype(np.float32) * sign)


def load_libsvm(fname: str, rank: int | None = None) -> SparseMat:
    """Load LibSVM-format data (reference: rabit-learn/utils/data.h:47-91).

    Mirrors the reference conventions: ``fname == "stdin"`` reads standard
    input, and a ``%d`` (or any printf int field) in the name is substituted
    with the caller's rank for per-rank shards.  ``feat_dim`` is the max
    feature index + 1 **of this shard** — callers allreduce(MAX) it, same as
    the reference apps do.
    """
    if fname == "stdin":
        text = sys.stdin.read()
    else:
        if "%" in fname:
            if rank is None:
                import rabit_tpu

                rank = rabit_tpu.get_rank()
            fname = fname % rank
        with open(fname) as f:
            text = f.read()

    indptr = [0]
    findex: list[int] = []
    fvalue: list[float] = []
    labels: list[float] = []
    feat_dim = 0
    for tok in text.split():
        if ":" in tok:
            fi, fv = tok.split(":", 1)
            fi = int(fi)
            findex.append(fi)
            fvalue.append(float(fv))
            feat_dim = max(feat_dim, fi)
        else:
            if labels:
                indptr.append(len(findex))
            labels.append(float(tok))
    check(bool(labels), "load_libsvm: no rows in %s", fname)
    indptr.append(len(findex))
    return SparseMat(
        indptr=np.asarray(indptr, np.int64),
        findex=np.asarray(findex, np.int32),
        fvalue=np.asarray(fvalue, np.float32),
        labels=np.asarray(labels, np.float32),
        feat_dim=feat_dim + 1,
    )


def save_matrix_txt(mat: np.ndarray, fname: str,
                    header: str | None = None) -> None:
    """Write a dense matrix as whitespace text, ``stdout`` supported
    (reference: Matrix::Print, rabit-learn/utils/data.h:115-132).
    ``header`` prepends one ``#``-comment line (skipped by
    ``np.loadtxt``) — used for model metadata like the k-means hash
    width."""
    out = sys.stdout if fname == "stdout" else open(fname, "w")
    try:
        if header is not None:
            out.write(f"# {header}\n")
        for row in np.atleast_2d(mat):
            out.write(" ".join(f"{v:g}" for v in row) + "\n")
    finally:
        if out is not sys.stdout:
            out.close()
