"""Split a LibSVM file into k per-rank row shards — and the shard math
the elastic layer reuses in memory.

Equivalent of the reference's shard-preparation tool
(reference: rabit-learn/linear/splitrows.py): rows are assigned to
shards pseudo-randomly with a fixed seed so runs are reproducible.
Output files are ``<out>.row0 .. <out>.row{k-1}``, the per-rank
``%d``-substitution naming the data loader understands
(reference: rabit-learn/utils/data.h:52-55; rabit_tpu.learn.data).

The assignment stream is the module's contract, not an implementation
detail: :func:`shard_indices` / :func:`rows_for_rank` replay the exact
``rng.randint`` sequence :func:`split` consumes, so in-memory shards
and on-disk shard files always agree row for row.  Elastic rescale
(doc/fault_tolerance.md "Elastic membership & tracker HA") leans on
this — after the world changes from ``k`` to ``k'`` ranks, every rank
recomputes ``rows_for_rank(n, rank, k', seed)`` and the new shards are
again an exact partition of the dataset: every row assigned to exactly
one rank, no row dropped or duplicated, deterministically for any
world size.

Usage: python -m rabit_tpu.learn.splitrows <fin> <out> <k>
"""
from __future__ import annotations

import random
import sys


def assignment_stream(k: int, seed: int = 10):
    """The canonical row→shard stream: yields the shard of row 0, row 1,
    ... for a world of ``k``.  Single source of truth for file splitting
    and in-memory (re)sharding."""
    rng = random.Random(seed)
    while True:
        yield rng.randint(0, k - 1)


def shard_indices(n_rows: int, k: int, seed: int = 10) -> list[list[int]]:
    """Row-index shards for an ``n_rows`` dataset across ``k`` ranks.

    By construction the shards are an exact partition of
    ``range(n_rows)`` for every ``k`` — the property elastic reshard
    correctness rests on (tests/test_elastic.py pins it for uneven
    4→6→3 worlds)."""
    stream = assignment_stream(k, seed)
    shards: list[list[int]] = [[] for _ in range(k)]
    for i in range(n_rows):
        shards[next(stream)].append(i)
    return shards


def rows_for_rank(n_rows: int, rank: int, k: int, seed: int = 10
                  ) -> list[int]:
    """One rank's row indices under the ``k``-way assignment — what an
    elastic worker calls after every rescale to re-shard its data."""
    stream = assignment_stream(k, seed)
    return [i for i in range(n_rows) if next(stream) == rank]


def split(fin: str, fout: str, k: int, seed: int = 10) -> list[str]:
    names = [f"{fout}.row{i}" for i in range(k)]
    stream = assignment_stream(k, seed)
    outs = [open(n, "w") for n in names]
    try:
        with open(fin) as f:
            for line in f:
                outs[next(stream)].write(line)
    finally:
        for f in outs:
            f.close()
    return names


def main(argv: list[str]) -> int:
    if len(argv) < 4:
        print("Usage: <fin> <fout> k")
        return 0
    split(argv[1], argv[2], int(argv[3]))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
