"""Split a LibSVM file into k per-rank row shards.

Equivalent of the reference's shard-preparation tool
(reference: rabit-learn/linear/splitrows.py): rows are assigned to
shards pseudo-randomly with a fixed seed so runs are reproducible.
Output files are ``<out>.row0 .. <out>.row{k-1}``, the per-rank
``%d``-substitution naming the data loader understands
(reference: rabit-learn/utils/data.h:52-55; rabit_tpu.learn.data).

Usage: python -m rabit_tpu.learn.splitrows <fin> <out> <k>
"""
from __future__ import annotations

import random
import sys


def split(fin: str, fout: str, k: int, seed: int = 10) -> list[str]:
    rng = random.Random(seed)
    names = [f"{fout}.row{i}" for i in range(k)]
    outs = [open(n, "w") for n in names]
    try:
        with open(fin) as f:
            for line in f:
                outs[rng.randint(0, k - 1)].write(line)
    finally:
        for f in outs:
            f.close()
    return names


def main(argv: list[str]) -> int:
    if len(argv) < 4:
        print("Usage: <fin> <fout> k")
        return 0
    split(argv[1], argv[2], int(argv[3]))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
