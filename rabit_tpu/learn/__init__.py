"""rabit-learn equivalent: distributed ML apps built on the framework API.

TPU-native re-design of the reference's mini ML toolkit
(reference: rabit-learn/ — kmeans, linear/logistic regression, generic
vector-free L-BFGS solver, LibSVM data utilities).  The compute paths are
JAX programs (jitted, MXU-shaped); cross-rank reduction and fault
tolerance go through :mod:`rabit_tpu.api`.
"""
from rabit_tpu.learn.data import SparseMat, load_libsvm, save_matrix_txt
from rabit_tpu.learn.lbfgs import LBFGSSolver, ObjFunction
from rabit_tpu.learn.linear import LinearModel, LinearObjFunction
from rabit_tpu.learn import boosting, histogram, kmeans

__all__ = [
    "SparseMat", "load_libsvm", "save_matrix_txt",
    "LBFGSSolver", "ObjFunction",
    "LinearModel", "LinearObjFunction",
    "boosting", "histogram", "kmeans",
]
